"""Island-model scaling over a virtual device mesh.

Runs the sharded island runner (``shard_map`` + ``ppermute`` ring
migration) for the SAME total workload — 8 islands × 2,048 × 64 OneMax —
over meshes of 1, 2, 4 and 8 virtual CPU devices, recording wall time per
epoch at each width. One real TPU chip cannot exercise multi-device
sharding, so this tracks the collective/sharding overhead trend (NOT
absolute accelerator speed: all virtual devices share the host's cores,
so ideal scaling is flat-to-modest here; on real hardware each width adds
chips). The artifact the trend guards: epoch time must not BLOW UP with
mesh width — a regression in the ppermute ring or the shard_map layout
shows up as superlinear growth.

Run: python tools/bench_islands_scaling.py   (forces CPU backend)
Prints one JSON line.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
from libpga_tpu.utils.compat import force_cpu_device_count  # noqa: E402

force_cpu_device_count(8)

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from libpga_tpu.objectives import onemax
from libpga_tpu.ops.crossover import uniform_crossover
from libpga_tpu.ops.mutate import make_point_mutate
from libpga_tpu.ops.step import make_breed
from libpga_tpu.parallel.islands import run_islands_stacked

ISLANDS, SIZE, LENGTH = 8, 2048, 64


def epoch_seconds(n_devices: int) -> float:
    mesh = Mesh(np.asarray(jax.devices()[:n_devices]), ("islands",))
    breed = make_breed(uniform_crossover, make_point_mutate(0.05))
    stacked = jax.random.uniform(
        jax.random.key(0), (ISLANDS, SIZE, LENGTH), dtype=jnp.float32
    )
    cache = {}

    def run(n):
        run_islands_stacked(
            breed, onemax, stacked, jax.random.key(1),
            n=n, m=5, pct=0.1, mesh=mesh, runner_cache=cache,
        )

    run(5)  # compile
    t0 = time.perf_counter()
    run(10)
    t_lo = time.perf_counter() - t0
    t0 = time.perf_counter()
    run(30)
    t_hi = time.perf_counter() - t0
    return max(t_hi - t_lo, 1e-9) / 20  # seconds per generation


def main() -> None:
    per_gen = {d: epoch_seconds(d) for d in (1, 2, 4, 8)}
    out = {
        "workload": f"{ISLANDS}x{SIZE}x{LENGTH} onemax, ring m=5 pct=0.1",
        "backend": "virtual-cpu-mesh",
        **{f"ms_per_gen_{d}dev": round(v * 1000, 3) for d, v in per_gen.items()},
        "growth_8dev_vs_1dev": round(per_gen[8] / per_gen[1], 2),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
