"""CPU structural tests for the alias-compatible ping-pong layout
(ISSUE 3 tentpole — ops/pallas_step.py).

The layout's correctness splits into pure ALGEBRA (the two parities'
row groupings partition the population, every grid step writes exactly
the rows it reads — the property that licenses ``input_output_aliases``
— and the alternation connects every row to every group) and KERNEL
structure (under zero interpret-mode PRNG bits every child copies its
cohort's rank-0 row, so the output is exactly predictable from the
algebra). Both are pinned here against ``pingpong_group_rows`` /
``pingpong_perm``, the single source of truth the BlockSpec index maps
mirror. Hardware-only properties (actual in-place buffer reuse, DMA
overlap, throughput) are round-8-pending on the next attached chip via
tools/ablate_floor.py's ``pingpong_alias`` / ``subblock`` variants.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libpga_tpu.objectives import onemax
from libpga_tpu.ops.pallas_step import (
    _BLOCK_BYTES_LIMIT,
    _SCOPED_VMEM_LIMIT,
    _blocks_fit,
    _scoped_vmem_bytes,
    make_pallas_breed,
    make_pallas_multigen,
    pingpong_admissible,
    pingpong_child_rows,
    pingpong_group_rows,
    pingpong_perm,
    pingpong_quantum,
)


def _interpret():
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.force_tpu_interpret_mode()


def _expected_rank0_copy(parity, Pp, W, q, K, P, genome_col, D=None, B=1):
    """Zero-PRNG-bits expectation: every READ deme's children copy its
    best alive row (scores strictly decreasing in physical row index →
    rank 0 = the deme's minimal alive physical row), and land at the
    WRITE-interleaved child rows (``pingpong_child_rows``) — the
    read-layout-A / write-layout-B crux of the scheme."""
    if D is None:
        D = W // K
    perm = pingpong_perm(parity, Pp, W, q)
    child = pingpong_child_rows(parity, Pp, K, q, D, B)
    out = np.zeros(Pp, np.float32)
    for c in range(Pp // K):
        rows = perm[c * K : (c + 1) * K]          # read cohort c
        dest = child[c * K : (c + 1) * K]         # its children's rows
        alive = rows[rows < P]
        best = alive.min() if alive.size else rows.min()
        # physical PAD rows may receive real children under the
        # interleave — harmless, the caller masks their scores and
        # slices [:P]; comparisons here only read [:P] too.
        out[dest] = genome_col[min(best, len(genome_col) - 1)]
    return out


class TestLayoutAlgebra:
    """Pure-numpy pins of the layout itself."""

    @pytest.mark.parametrize("parity", [0, 1])
    @pytest.mark.parametrize(
        "Pp,W,q", [(4096, 512, 8), (2048, 256, 8), (8192, 1024, 16)]
    )
    def test_groups_partition_population(self, parity, Pp, W, q):
        S = Pp // W
        seen = np.zeros(Pp, bool)
        for i in range(S):
            rows = pingpong_group_rows(parity, i, W=W, S=S, q=q)
            assert rows.shape == (W,)
            assert not seen[rows].any(), "groups must be disjoint"
            seen[rows] = True
        assert seen.all(), "groups must cover every row"

    @pytest.mark.parametrize(
        "Pp,W,q", [(4096, 512, 8), (8192, 1024, 16)]
    )
    def test_alias_safety_write_set_equals_read_set(self, Pp, W, q):
        """THE aliasing license: for each parity, the in and out
        BlockSpecs are the same index map, i.e. step i's write rows ==
        its read rows. At algebra level both are pingpong_group_rows;
        equality across parities of the UNION (each a partition) plus
        the kernel-structure tests below (which verify the kernel's
        actual writes land on the algebra's rows) pin it."""
        S = Pp // W
        K, D = 128, W // 128
        for parity in (0, 1):
            perm = pingpong_perm(parity, Pp, W, q)
            child = pingpong_child_rows(parity, Pp, K, q, D)
            for i in range(S):
                rows = pingpong_group_rows(parity, i, W=W, S=S, q=q)
                # read map: group i's slot range is exactly these rows
                np.testing.assert_array_equal(
                    perm[i * W : (i + 1) * W], rows
                )
                # write map: the interleaved child placement PERMUTES
                # the same row set — writes never leave the step's rows
                assert set(child[i * W : (i + 1) * W]) == set(rows), (
                    f"parity {parity} group {i}: children escaped"
                )

    def test_parity1_is_a_strided_comb(self):
        Pp, W, q = 4096, 512, 8
        S = Pp // W
        rows = pingpong_group_rows(1, 3, W=W, S=S, q=q)
        # A chunks of q consecutive rows at stride S*q, offset i*q
        A = W // q
        for a in range(A):
            chunk = rows[a * q : (a + 1) * q]
            np.testing.assert_array_equal(
                chunk, np.arange(a * S * q + 3 * q, a * S * q + 4 * q)
            )

    def test_admissibility_gate(self):
        # A >= S <=> W^2 >= Pp*q — the full-coverage mixing condition
        assert pingpong_admissible(4096, 1 << 20, 8)       # f32 1M D=8 K=512
        assert not pingpong_admissible(2048, 1 << 20, 16)  # bf16 1M D=4 K=512
        assert pingpong_admissible(4096, 1 << 20, 16)      # bf16 1M D=8 K=512
        assert not pingpong_admissible(512, 1 << 20, 8)    # D=1 at 1M
        assert not pingpong_admissible(0, 1024, 8)
        assert not pingpong_admissible(513, 1024, 8)       # q-misaligned
        assert not pingpong_admissible(384, 1024, 8)       # W does not divide

    def test_quantum_is_the_dtype_sublane_tile(self):
        assert pingpong_quantum(jnp.float32) == 8
        assert pingpong_quantum(jnp.bfloat16) == 16

    def test_lineage_reaches_every_cohort_in_few_generations(self):
        """THE mixing pin, at the granularity that matters: selection
        COHORTS (K rows), through the real read maps (pingpong_perm)
        and write maps (pingpong_child_rows). A lineage starting in any
        single cohort must reach EVERY cohort of both parities within a
        few alternating generations — the property whose absence (the
        read==write-per-deme variant) fragments the population into
        closed super-blocks and stalls takeover (see
        tools/selection_equivalence.py --simulate)."""
        Pp, K, D, q = 4096, 128, 4, 8  # W=512, S=8, A=64 >= 8
        W = D * K
        C = Pp // K  # cohorts per parity
        maps = {}
        for parity in (0, 1):
            perm = pingpong_perm(parity, Pp, W, q).reshape(C, K)
            child = pingpong_child_rows(parity, Pp, K, q, D).reshape(C, K)
            row_cohort = np.empty(Pp, np.int64)
            for c in range(C):
                row_cohort[perm[c]] = c
            maps[parity] = (perm, child, row_cohort)
        # breadth-first lineage spread from cohort 0, alternating parity
        rows = set(maps[0][0][0])  # rows of parity-0 cohort 0
        for gen in range(6):
            parity = gen % 2
            perm, child, row_cohort = maps[parity]
            cohorts = {row_cohort[r] for r in rows}
            rows = set()
            for c in cohorts:
                rows.update(child[c])  # children land here
        final = {maps[0][2][r] for r in rows}
        assert final == set(range(C)), (
            f"lineage reached only {len(final)}/{C} cohorts in 6 gens"
        )

    def test_inadmissible_shape_really_disconnects(self):
        """The gate's reason for existing: at A < S the two partitions
        leave row components that NEVER exchange individuals (the
        middle index bits are never regrouped), so the layout must not
        ship there."""
        Pp, W, q = 4096, 128, 8  # A=16 < S=32 — inadmissible
        S = Pp // W
        assert not pingpong_admissible(W, Pp, q)
        # union-find over the two partitions' groups
        parent = list(range(Pp))

        def find(x):
            while parent[x] != x:
                parent[x] = parent[parent[x]]
                x = parent[x]
            return x

        def union(a, b):
            parent[find(a)] = find(b)

        for parity in (0, 1):
            for i in range(S):
                rows = pingpong_group_rows(parity, i, W=W, S=S, q=q)
                for r in rows[1:]:
                    union(int(rows[0]), int(r))
        comps = {find(r) for r in range(Pp)}
        assert len(comps) > 1, "expected disconnected components below gate"


class TestOneGenKernel:
    """Interpret-mode structure: the kernel's writes land exactly where
    the algebra says, for BOTH parities, and in place."""

    @pytest.mark.parametrize("parity", [0, 1])
    def test_rank0_structure_matches_algebra(self, parity):
        P, L, K, D = 512, 16, 128, 2
        with _interpret():
            breed = make_pallas_breed(
                P, L, deme_size=K, mutation_rate=0.0,
                fused_obj=onemax.kernel_rowwise,
                _demes_per_step=D, _layout="pingpong",
            )
            assert breed is not None and breed.layout == "pingpong"
            assert breed.parities == 2
            g = jnp.broadcast_to(
                jnp.arange(P, dtype=jnp.float32)[:, None], (P, L)
            ) / P
            s = -jnp.arange(P, dtype=jnp.float32)  # rank0 = min physical row
            g2, s2 = breed(g, s, jax.random.key(0), parity=parity)
        W = breed.D * breed.K
        q = pingpong_quantum(jnp.float32)
        expect = _expected_rank0_copy(
            parity, breed.Pp, W, q, breed.K, P, np.arange(P) / P
        )
        np.testing.assert_allclose(
            np.asarray(g2)[:, 0], expect[:P], atol=2e-5, rtol=0
        )
        # fused scores travel with their genome rows (physical order)
        np.testing.assert_allclose(
            np.asarray(s2), np.asarray(g2).sum(axis=1), atol=1e-4, rtol=0
        )

    def test_children_never_leave_their_group(self):
        """Alias safety, kernel-level: encode group membership in the
        genes and check every output row's value originated in its own
        group — a step writing another step's rows would break this."""
        P, L, K, D = 1024, 8, 128, 2
        with _interpret():
            breed = make_pallas_breed(
                P, L, deme_size=K, mutation_rate=0.0,
                fused_obj=onemax.kernel_rowwise,
                _demes_per_step=D, _layout="pingpong",
            )
            W = breed.D * breed.K
            S = breed.Pp // W
            q = pingpong_quantum(jnp.float32)
            for parity in (0, 1):
                member = np.zeros(P, np.float32)
                for i in range(S):
                    rows = pingpong_group_rows(parity, i, W=W, S=S, q=q)
                    member[rows[rows < P]] = (i + 1) / (S + 1)
                g = jnp.broadcast_to(
                    jnp.asarray(member)[:, None], (P, L)
                ).astype(jnp.float32)
                s = jax.random.normal(jax.random.key(parity), (P,))
                g2, _ = breed(g, s, jax.random.key(1), parity=parity)
                np.testing.assert_allclose(
                    np.asarray(g2)[:, 0], member, atol=2e-5, rtol=0,
                    err_msg=f"parity {parity}: children crossed groups",
                )

    def test_in_place_aliasing_is_declared(self):
        """The shipped default must carry input_output_aliases — pinned
        by jaxpr inspection (interpret mode executes it functionally;
        hardware reuses the buffer)."""
        P, L, K = 512, 16, 128
        with _interpret():
            breed = make_pallas_breed(
                P, L, deme_size=K, fused_obj=onemax.kernel_rowwise,
            )
            assert breed.layout == "pingpong", "pingpong must be the default"
            gp = jax.random.uniform(jax.random.key(0), (breed.Pp, breed.Lp))
            sp = jnp.sum(gp[:, :L], axis=1)
            jaxpr = jax.make_jaxpr(
                lambda g, s: breed.padded(g, s, jax.random.key(1))
            )(gp, sp)
        txt = str(jaxpr)
        assert "input_output_aliases" in txt and "(3, 0)" in txt, (
            "genome input must alias the genome output"
        )

    def test_fused_default_is_pingpong_nonfused_is_riffle(self):
        with _interpret():
            fused = make_pallas_breed(
                512, 16, deme_size=128, fused_obj=onemax.kernel_rowwise
            )
            plain = make_pallas_breed(512, 16, deme_size=128)
        assert fused.layout == "pingpong"
        assert plain.layout == "riffle"

    def test_explicit_pingpong_raises_when_gate_fails(self):
        # D=1 at a shape where W=K fails A >= S
        with pytest.raises(ValueError, match="pingpong"):
            make_pallas_breed(
                1 << 15, 16, deme_size=128, _demes_per_step=1,
                fused_obj=onemax.kernel_rowwise, _layout="pingpong",
            )

    def test_layout_ablations_are_riffle_only(self):
        with pytest.raises(ValueError, match="riffle"):
            make_pallas_breed(
                512, 16, deme_size=128, fused_obj=onemax.kernel_rowwise,
                _layout="pingpong", _ablate=("no_riffle",),
            )


class TestPaddedPopulation:
    """Satellite: the round-2 'pad rows are inert' guarantees extended
    to BOTH parities of the new layout — pad rows excluded from
    tournaments, pad lanes zero."""

    @pytest.mark.parametrize("parity", [0, 1])
    def test_pads_never_selected_and_pad_lanes_zero(self, parity):
        # P=300 at K=128 pads to 384; D=1 would fail the gate, so pick
        # P=1000 -> Pp=1024, G=8, D=2: W=256, S=4, A=32 >= 4.
        P, L, K, D = 1000, 12, 128, 2
        with _interpret():
            breed = make_pallas_breed(
                P, L, deme_size=K, mutation_rate=0.0,
                fused_obj=onemax.kernel_rowwise,
                _demes_per_step=D, _layout="pingpong",
            )
            assert breed.Pp == 1024
            g = jnp.broadcast_to(
                jnp.arange(P, dtype=jnp.float32)[:, None], (P, L)
            ) / P
            # NaN scores on real rows still must not select pads
            s = -jnp.arange(P, dtype=jnp.float32)
            g2, s2 = breed(g, s, jax.random.key(0), parity=parity)
            # padded variant: the pad tail itself
            gp = jnp.pad(g, ((0, breed.Pp - P), (0, breed.Lp - L)))
            sp = jnp.pad(s, (0, breed.Pp - P), constant_values=-jnp.inf)
            gp2, sp2 = breed.padded(gp, sp, jax.random.key(0), parity=parity)
        g2 = np.asarray(g2)
        # zero-bits children copy their deme's best ALIVE row — never a
        # pad (pads carry zero genes; real genomes here are >= 1/P only
        # for rows >= 1, so check value membership in real rows)
        W = breed.D * breed.K
        q = pingpong_quantum(jnp.float32)
        expect = _expected_rank0_copy(
            parity, breed.Pp, W, q, breed.K, P, np.arange(P) / P
        )
        np.testing.assert_allclose(g2[:, 0], expect[:P], atol=2e-5, rtol=0)
        # pad-row scores masked, pad LANES zero in the padded output
        sp2, gp2 = np.asarray(sp2), np.asarray(gp2)
        assert np.all(np.isneginf(sp2[P:]))
        assert np.all(gp2[:, L:] == 0.0), "pad lanes must stay zero"

    @pytest.mark.parametrize("parity", [0, 1])
    def test_padded_gaussian_keeps_pad_lanes_zero(self, parity):
        P, L, K, D = 1000, 12, 128, 2
        with _interpret():
            breed = make_pallas_breed(
                P, L, deme_size=K, mutation_rate=1.0,
                mutation_sigma=0.5, mutate_kind="gaussian",
                fused_obj=onemax.kernel_rowwise,
                _demes_per_step=D, _layout="pingpong",
            )
            gp = jnp.pad(
                jax.random.uniform(jax.random.key(2), (P, L)),
                ((0, breed.Pp - P), (0, breed.Lp - L)),
            )
            sp = jnp.pad(
                jnp.sum(gp[:P, :L], axis=1), (0, breed.Pp - P),
                constant_values=-jnp.inf,
            )
            gp2, _ = breed.padded(gp, sp, jax.random.key(0), parity=parity)
        assert np.all(np.asarray(gp2)[:, L:] == 0.0)


class TestElitismAndMultigen:
    def test_elitism_epilogue_both_parities(self):
        """Fused elitism with the in-place layout: elites are gathered
        BEFORE the kernel (no post-call read of the pre-breed buffer)
        and land in physical rows 0..e-1 with their scores."""
        P, L, K = 256, 8, 128
        genomes = (
            jnp.broadcast_to(
                jnp.arange(P, dtype=jnp.float32)[:, None], (P, L)
            ) / P
        )
        scores = jnp.zeros((P,), jnp.float32).at[131].set(9.0).at[7].set(5.0)
        with _interpret():
            breed = make_pallas_breed(
                P, L, deme_size=K, mutation_rate=0.0, elitism=2,
                fused_obj=onemax.kernel_rowwise, _layout="pingpong",
            )
            for parity in (0, 1):
                g2, s2 = breed(genomes, scores, jax.random.key(0),
                               parity=parity)
                g2, s2 = np.asarray(g2), np.asarray(s2)
                gn = np.asarray(genomes)
                np.testing.assert_array_equal(g2[0], gn[131])
                np.testing.assert_array_equal(g2[1], gn[7])
                assert s2[0] == 9.0 and s2[1] == 5.0

    def test_multigen_zero_steps_is_the_interleave_permutation(self):
        """steps=0 passes the population through the launch-boundary
        write interleave ONLY: output row ``child_rows[x]`` must be
        input row ``perm[x]`` exactly, scores aligned — pinning the
        writeback map against the algebra."""
        P, L = 512, 20
        with _interpret():
            bm = make_pallas_multigen(
                P, L, deme_size=128, fused_obj=onemax.kernel_rowwise,
                fused_consts=tuple(
                    getattr(onemax, "kernel_rowwise_consts", ())
                ),
                _layout="pingpong",
            )
            assert bm is not None and bm.layout == "pingpong"
            g = jax.random.uniform(jax.random.key(1), (P, L))
            s = jnp.sum(g, axis=1)
            q = pingpong_quantum(jnp.float32)
            W = bm.D * bm.K
            for parity in (0, 1):
                g0, s0 = bm(g, s, jax.random.key(0), 0, None, None, parity)
                g0, s0 = np.asarray(g0), np.asarray(s0)
                perm = pingpong_perm(parity, bm.Pp, W, q)
                child = pingpong_child_rows(parity, bm.Pp, bm.K, q, bm.D)
                gn = np.asarray(g)
                np.testing.assert_array_equal(g0[child], gn[perm])
                np.testing.assert_allclose(
                    np.asarray(s0)[child], np.asarray(s)[perm], rtol=1e-5
                )

    @pytest.mark.parametrize("parity", [0, 1])
    def test_multigen_rank0_structure(self, parity):
        P, L, K, D = 1024, 12, 128, 2
        with _interpret():
            bm = make_pallas_multigen(
                P, L, deme_size=K, mutation_rate=0.0,
                fused_obj=onemax.kernel_rowwise,
                _demes_per_step=D, _layout="pingpong",
            )
            assert bm.layout == "pingpong" and bm.D == D
            g = jnp.broadcast_to(
                jnp.arange(P, dtype=jnp.float32)[:, None], (P, L)
            ) / P
            s = -jnp.arange(P, dtype=jnp.float32)
            g2, s2 = bm(g, s, jax.random.key(0), 1, None, None, parity)
        W = bm.D * bm.K
        q = pingpong_quantum(jnp.float32)
        expect = _expected_rank0_copy(
            parity, bm.Pp, W, q, bm.K, P, np.arange(P) / P
        )
        np.testing.assert_allclose(
            np.asarray(g2)[:, 0], expect[:P], atol=2e-5, rtol=0
        )
        np.testing.assert_allclose(
            np.asarray(s2), np.asarray(g2).sum(axis=1), atol=1e-4, rtol=0
        )

    def test_multigen_padded_alive_mask(self):
        """Padded multigen under ping-pong: the static alive mask
        replaces the positional tail; children stay real-rooted and
        scores consistent for both parities."""
        P, L, K, D = 1000, 12, 128, 2
        with _interpret():
            bm = make_pallas_multigen(
                P, L, deme_size=K, fused_obj=onemax.kernel_rowwise,
                _demes_per_step=D, _layout="pingpong",
            )
            assert bm.Pp == 1024 and bm.layout == "pingpong"
            g = jax.random.uniform(jax.random.key(2), (P, L))
            s = jnp.sum(g, axis=1)
            for parity in (0, 1):
                g2, s2 = bm(g, s, jax.random.key(0), 3, None, None, parity)
                assert np.all(np.isfinite(np.asarray(s2)))
                np.testing.assert_allclose(
                    np.asarray(s2), np.asarray(g2).sum(axis=1), rtol=1e-4
                )

    def test_multigen_padded_elitism_falls_back_to_riffle(self):
        """A pad row can occupy a parity-1 cohort's elite slot, so the
        auto resolver must keep padded+elitism multigen on the riffle."""
        with _interpret():
            bm = make_pallas_multigen(
                1000, 12, deme_size=128, elitism=2,
                fused_obj=onemax.kernel_rowwise, _demes_per_step=2,
            )
        assert bm.layout == "riffle"


class TestSubblockPipeline:
    """The second tentpole lever: B sub-blocks per grid step via the
    manual double-buffered DMA pipeline."""

    def test_grid_shrinks_2x_at_bench_shape_constant_vmem(self):
        """Acceptance pin, pure arithmetic: at the 1M x 100 f32 bench
        shape the riffle kernel needs G/D = 256 grid steps (VMEM caps
        D at 8); sub-block B=2 halves that AND B=4 quarters it, at the
        SAME per-sub-block scoped-VMEM model (the streamed scratch pair
        equals Mosaic's double-buffered block allowance)."""
        K, Lp, P = 512, 128, 1 << 20
        G = P // K  # 2048
        # VMEM model: D=8 fits, D=16 does not — the dispatch floor
        assert _blocks_fit(K, 8, Lp, 4) and not _blocks_fit(K, 16, Lp, 4)
        riffle_steps = G // 8
        assert riffle_steps == 256
        # sub-blocking at D=8 per sub-block keeps the same scoped model
        assert _scoped_vmem_bytes(K, 8, Lp, 4) <= _SCOPED_VMEM_LIMIT
        assert 4 * 8 * K * Lp * 4 <= _BLOCK_BYTES_LIMIT
        for B in (2, 4):
            assert G % (B * 8) == 0
            assert riffle_steps // B * B == riffle_steps
            assert riffle_steps / (G // (B * 8)) == B
        assert G // (2 * 8) == 128 <= riffle_steps // 2

    def test_subblock_factory_reports_grid_reduction(self):
        P, L, K, D = 1024, 16, 128, 2
        with _interpret():
            b1 = make_pallas_breed(
                P, L, deme_size=K, fused_obj=onemax.kernel_rowwise,
                _demes_per_step=D, _layout="pingpong",
            )
            b2 = make_pallas_breed(
                P, L, deme_size=K, fused_obj=onemax.kernel_rowwise,
                _demes_per_step=D, _layout="pingpong", _subblock=2,
            )
        assert b1.grid_steps == 4 and b2.grid_steps == 2
        assert b2.subblock == 2 and b2.D == 2 * D

    @pytest.mark.parametrize("parity", [0, 1])
    def test_subblock_children_match_algebra(self, parity):
        """The streamed pipeline must produce the same structural
        children as the algebra predicts for its (wider) groups."""
        P, L, K, D, B = 1024, 12, 128, 2, 2
        with _interpret():
            breed = make_pallas_breed(
                P, L, deme_size=K, mutation_rate=0.0,
                fused_obj=onemax.kernel_rowwise,
                _demes_per_step=D, _layout="pingpong", _subblock=B,
            )
            assert breed.subblock == B and breed.D == B * D
            g = jnp.broadcast_to(
                jnp.arange(P, dtype=jnp.float32)[:, None], (P, L)
            ) / P
            s = -jnp.arange(P, dtype=jnp.float32)
            g2, s2 = breed(g, s, jax.random.key(0), parity=parity)
        W = breed.D * breed.K
        q = pingpong_quantum(jnp.float32)
        expect = _expected_rank0_copy(
            parity, breed.Pp, W, q, breed.K, P, np.arange(P) / P,
            D=breed.D // breed.subblock, B=breed.subblock,
        )
        np.testing.assert_allclose(
            np.asarray(g2)[:, 0], expect[:P], atol=2e-5, rtol=0
        )
        np.testing.assert_allclose(
            np.asarray(s2), np.asarray(g2).sum(axis=1), atol=1e-4, rtol=0
        )

    def test_multigen_ignores_subblock(self):
        with _interpret():
            bm = make_pallas_multigen(
                512, 16, deme_size=128, fused_obj=onemax.kernel_rowwise,
                _subblock=4,
            )
        assert bm is not None and bm.subblock == 1


class TestRunLoopParity:
    def test_multigen_run_loop_alternates_and_lands_exactly(self):
        """The chunked run loop still lands exactly on n with the
        parity-alternating lax.cond dispatch in the carry."""
        from libpga_tpu.objectives import get as get_obj
        from libpga_tpu.ops.pallas_step import (
            _multigen_run_loop, make_pallas_multigen,
        )

        obj = get_obj("onemax")
        P, L = 512, 20
        with _interpret():
            bm = make_pallas_multigen(
                P, L, deme_size=128, fused_obj=obj.kernel_rowwise,
                fused_consts=tuple(
                    getattr(obj, "kernel_rowwise_consts", ())
                ),
                _layout="pingpong",
            )
            assert bm.layout == "pingpong"
            run = _multigen_run_loop(obj, bm, P, L, 3, donate=False)
            g = jax.random.uniform(jax.random.key(1), (P, L))
            g2, s2, gens = run(
                g, jax.random.key(0), jnp.int32(10), jnp.float32(jnp.inf),
                bm.default_params,
            )
        assert int(gens) == 10
        np.testing.assert_allclose(
            np.asarray(s2), np.asarray(jnp.sum(g2, axis=1)), rtol=1e-4
        )

    def test_island_stacked_epoch_parity_pairs(self):
        """run_islands_stacked over a ping-pong breed: the epoch's
        pair-scan (+ odd tail) keeps carried scores consistent with
        the carried genomes."""
        from libpga_tpu.parallel.islands import run_islands_stacked

        I, S, L, K = 2, 512, 20, 128
        with _interpret():
            breed = make_pallas_breed(
                S, L, deme_size=K, mutation_rate=0.0,
                fused_obj=onemax.kernel_rowwise, _layout="pingpong",
            )
            assert breed.fused and breed.parities == 2
            stacked = jax.random.uniform(jax.random.key(0), (I, S, L))
            genomes, scores, gens = run_islands_stacked(
                breed, onemax, stacked, jax.random.key(1), n=3, m=3,
                pct=0.05,
            )
        assert gens == 3
        np.testing.assert_allclose(
            np.asarray(scores), np.asarray(genomes).sum(axis=2),
            atol=2e-4, rtol=0,
        )


class TestAblateFlagValidation:
    """Satellite: unknown ablation flags must raise, naming the valid
    set, instead of silently measuring the full kernel."""

    def test_unknown_flag_raises_with_valid_set(self):
        with pytest.raises(ValueError) as ei:
            make_pallas_breed(512, 16, deme_size=128, _ablate=("no_rifle",))
        msg = str(ei.value)
        assert "no_rifle" in msg and "no_riffle" in msg
        assert "copy_only" in msg  # names the valid set

    def test_unknown_flag_raises_on_multigen(self):
        with pytest.raises(ValueError, match="unknown ablation flag"):
            make_pallas_multigen(
                512, 16, deme_size=128, fused_obj=onemax.kernel_rowwise,
                _ablate=("serail_grid",),
            )

    def test_known_flags_still_accepted(self):
        with _interpret():
            b = make_pallas_breed(
                512, 16, deme_size=128,
                _ablate=("copy_only", "no_rank_sort"),
            )
        assert b is not None
