"""Serving-grade observability substrate (ISSUE 6): the metrics
registry (histograms / gauges / counters + exporters), the flight
recorder, and the SLO configuration — all host-side (nothing here may
touch a traced program; the StableHLO byte-identity gates in
test_telemetry.py prove the run loops can't see this layer)."""

import json
import math
import threading

import numpy as np
import pytest

from libpga_tpu.utils import metrics as M
from libpga_tpu.utils import telemetry as T


# ---------------------------------------------------------------- bounds


def test_log_bounds_shape_and_validation():
    b = M.log_bounds(0.01, 1e6, 5)
    assert b[0] == 0.01 and b[-1] >= 1e6
    assert all(x2 > x1 for x1, x2 in zip(b, b[1:]))
    assert M.DEFAULT_BOUNDS == b  # the registry-wide shared layout
    with pytest.raises(ValueError):
        M.log_bounds(0, 10)
    with pytest.raises(ValueError):
        M.log_bounds(10, 1)
    with pytest.raises(ValueError):
        M.log_bounds(1, 10, 0)


# ------------------------------------------------------------- histogram


def test_histogram_percentiles_vs_numpy():
    """Log-spaced buckets bound percentile error by the bucket width:
    at 5 buckets/decade an estimate can be off by at most a factor of
    10^(1/5) ~ 1.585 from the true order statistic. Checked against
    numpy on heavy-tailed samples — the latency-shaped case."""
    rng = np.random.default_rng(7)
    for scale in (0.5, 3.0):
        xs = rng.lognormal(scale, 1.2, 10_000)
        h = M.Histogram()
        for x in xs:
            h.observe(x)
        for q in (50, 90, 95, 99):
            est = h.percentile(q)
            true = float(np.percentile(xs, q))
            assert true / 1.6 <= est <= true * 1.6, (q, est, true)


def test_histogram_percentile_edge_cases():
    h = M.Histogram(bounds=(1.0, 10.0, 100.0))
    assert math.isnan(h.percentile(50))  # empty
    h.observe(5.0)
    # one sample: every percentile is that sample (clamped to min/max)
    assert h.percentile(1) == h.percentile(99) == 5.0
    h.observe(float("nan"))  # ignored, must not poison sum
    assert h.count == 1 and h.sum == 5.0
    h.observe(1e9)  # overflow bucket, clamped to recorded max
    assert h.percentile(100) == 1e9
    with pytest.raises(ValueError):
        h.percentile(101)
    with pytest.raises(ValueError):
        M.Histogram(bounds=(2.0, 1.0))


def test_snapshot_merge_associative_on_random_splits():
    """Merge must be associative + commutative so per-worker snapshots
    can combine in any tree order (the fleet-aggregation property)."""
    rng = np.random.default_rng(3)
    xs = rng.lognormal(2.0, 1.0, 6_000)
    parts = [M.Histogram() for _ in range(4)]
    whole = M.Histogram()
    assignment = rng.integers(0, 4, xs.shape[0])
    for x, i in zip(xs, assignment):
        parts[i].observe(x)
        whole.observe(x)
    a, b, c, d = (p.snapshot() for p in parts)
    m1 = a.merge(b).merge(c).merge(d)
    m2 = a.merge(b.merge(c.merge(d)))
    m3 = d.merge(c).merge(b.merge(a))
    ref = whole.snapshot()
    assert m1.counts == m2.counts == m3.counts == ref.counts
    assert m1.min == ref.min and m1.max == ref.max
    assert math.isclose(m1.sum, ref.sum, rel_tol=1e-9)
    assert math.isclose(m2.sum, m3.sum, rel_tol=1e-9)
    # percentiles are a pure function of the merged state
    assert m1.percentile(99) == m2.percentile(99) == m3.percentile(99)
    with pytest.raises(ValueError):
        a.merge(M.Histogram(bounds=(1.0, 2.0)).snapshot())


def test_snapshot_dict_round_trip():
    h = M.Histogram()
    for v in (0.5, 5.0, 500.0):
        h.observe(v)
    snap = h.snapshot()
    d = snap.as_dict()
    json.dumps(d)  # JSON-able
    back = M.HistogramSnapshot.from_dict(d)
    assert back == snap
    # empty round trip keeps the empty sentinel semantics
    e = M.Histogram(bounds=(1.0, 2.0)).snapshot()
    assert M.HistogramSnapshot.from_dict(e.as_dict()) == e


# --------------------------------------------------- gauges and counters


def test_gauge_and_counter_under_threads():
    """The serving flusher thread and submitter threads hit the same
    gauges/counters; increments must not be lost."""
    g = M.Gauge()
    c = M.Counter()
    h = M.Histogram()
    N, WORKERS = 2_000, 4

    def work():
        for _ in range(N):
            g.add(1)
            c.bump()
            h.observe(1.0)

    threads = [threading.Thread(target=work) for _ in range(WORKERS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert g.value == N * WORKERS
    assert c.value == N * WORKERS
    assert h.count == N * WORKERS
    g.set(7.5)
    assert g.value == 7.5
    with pytest.raises(ValueError):
        c.bump(-1)


def test_counters_bump_listener_isolation_warns_once():
    """Satellite (ISSUE 6): a raising Counters listener can't break
    cache/queue accounting, and warns ONCE per failing listener — not
    once per bump (hot-path counters would bury diagnostics)."""
    import warnings

    cs = M.Counters()
    seen = []

    def bad(name, value):
        raise RuntimeError("boom")

    cs.add_listener(bad)
    cs.add_listener(lambda name, value: seen.append((name, value)))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        for _ in range(5):
            cs.bump("hits")
    assert cs.get("hits") == 5  # accounting survived
    assert seen[-1] == ("hits", 5)  # later listeners still fire
    assert sum("boom" in str(x.message) for x in w) == 1  # once, not 5
    # re-adding after removal warns again (fresh registration)
    cs.remove_listener(bad)
    cs.add_listener(bad)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        cs.bump("hits")
    assert sum("boom" in str(x.message) for x in w) == 1


def test_counters_bump_thread_safe():
    cs = M.Counters()
    N, WORKERS = 2_000, 4
    threads = [
        threading.Thread(
            target=lambda: [cs.bump("n") for _ in range(N)]
        )
        for _ in range(WORKERS)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert cs.get("n") == N * WORKERS


# -------------------------------------------------------------- registry


def test_registry_series_identity_labels_and_kinds():
    r = M.MetricsRegistry()
    assert r.counter("x") is r.counter("x")
    assert r.gauge("g", bucket="a") is r.gauge("g", bucket="a")
    assert r.gauge("g", bucket="a") is not r.gauge("g", bucket="b")
    assert r.histogram("h") is r.histogram("h")
    with pytest.raises(ValueError):
        r.gauge("x")  # kind collision, even for a new label set
    r.reset()
    r.gauge("x")  # fine after reset


def test_registry_snapshot_and_prometheus_lint():
    r = M.MetricsRegistry()
    r.counter("serving.tickets_done").bump(3)
    r.gauge("serving.queue.depth").set(2)
    r.gauge("serving.bucket.pending", bucket="b01").set(4)
    h = r.histogram("serving.ticket.e2e_ms")
    for v in (1.0, 10.0, 100.0, 1e9):
        h.observe(v)
    snap = r.snapshot()
    json.dumps(snap)
    assert snap["schema"] == M.MetricsRegistry.SNAPSHOT_SCHEMA
    [hrec] = snap["histograms"]
    assert hrec["count"] == 4 and hrec["p50"] is not None
    text = r.to_prometheus()
    assert M.lint_prometheus(text) == []
    # snapshot-driven rendering equals live rendering
    assert M.prometheus_text(snap) == text
    # exposition carries the cumulative +Inf bucket = count
    assert 'le="+Inf"} 4' in text


def test_lint_catches_malformed_expositions():
    good = "# TYPE pga_x counter\npga_x 3\n"
    assert M.lint_prometheus(good) == []
    assert M.lint_prometheus("pga x 3\n")  # bad name
    assert M.lint_prometheus("pga_x three\n")  # bad value
    assert M.lint_prometheus('pga_x{le=1} 3\n')  # unquoted label
    # non-cumulative buckets
    bad_hist = (
        'pga_h_bucket{le="1.0"} 5\n'
        'pga_h_bucket{le="2.0"} 3\n'
        'pga_h_bucket{le="+Inf"} 5\n'
    )
    assert any("cumulative" in e for e in M.lint_prometheus(bad_hist))
    # missing +Inf
    assert any(
        "+Inf" in e
        for e in M.lint_prometheus('pga_h_bucket{le="1.0"} 5\n')
    )
    # +Inf bucket disagreeing with _count
    bad_count = (
        'pga_h_bucket{le="+Inf"} 5\n'
        "pga_h_count 6\n"
    )
    assert any("_count" in e for e in M.lint_prometheus(bad_count))


# -------------------------------------------------------- flight recorder


def test_flight_recorder_ring_is_bounded():
    fr = T.FlightRecorder(capacity=8)
    for i in range(20):
        fr.note("compile", {"what": f"w{i}"})
    recs = fr.records()
    assert len(recs) == 8
    assert recs[0]["what"] == "w12" and recs[-1]["what"] == "w19"
    fr.clear()
    assert fr.records() == []
    with pytest.raises(ValueError):
        T.FlightRecorder(capacity=0)


def test_flight_recorder_dump_is_schema_valid(tmp_path):
    fr = T.FlightRecorder(capacity=16, dump_dir=str(tmp_path))
    fr.note("compile", {"what": "serving_mega_run"})
    fr.note("retry", {"attempt": 1, "error": "boom"})
    path = fr.dump(reason="dead_letter")
    assert path in fr.dumps
    recs = T.validate_log(path)  # schema-valid against EVENT_FIELDS
    kinds = [r["event"] for r in recs]
    assert kinds == ["compile", "retry", "metrics_snapshot", "flight_dump"]
    assert recs[-1]["reason"] == "dead_letter"
    assert recs[-1]["records"] == 2
    assert isinstance(recs[-2]["metrics"], dict)  # live registry context


def test_flight_note_and_dump_never_raise(tmp_path, monkeypatch):
    """The recorder is the diagnostic of last resort: a broken dump
    target must warn, not mask the failure being recorded."""
    import warnings

    fr = T.FlightRecorder(dump_dir=str(tmp_path / "missing" / "deep"))
    fr.note("compile", {"what": "x"})
    target = tmp_path / "not-a-dir"
    target.write_text("file, not dir")
    fr.dump_dir = str(target)  # makedirs will fail
    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        try:
            fr.dump(reason="r")
        except Exception as e:  # pragma: no cover
            pytest.fail(f"dump raised {e!r}")
    T.flight_note("compile", {"what": "y"})  # module helpers: no raise
    assert T.flight_dump("manual") is not None


# ------------------------------------------------------------ SLO config


def test_slo_config_validation():
    from libpga_tpu import SLOConfig

    SLOConfig()  # all-None = unchecked
    SLOConfig(p99_latency_ms=10.0, max_queue_wait_ms=0.0, min_samples=1)
    with pytest.raises(ValueError):
        SLOConfig(p99_latency_ms=0.0)
    with pytest.raises(ValueError):
        SLOConfig(max_queue_wait_ms=-1.0)
    with pytest.raises(ValueError):
        SLOConfig(min_samples=0)
