"""Objective-function tests: known optima, reference-driver semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libpga_tpu import objectives
from libpga_tpu.objectives import (
    onemax,
    onemax_bits,
    sphere,
    rastrigin,
    ackley,
    default_knapsack,
    make_knapsack,
    make_tsp,
    make_nk_landscape,
    make_deceptive_trap,
)
from libpga_tpu.objectives.classic import random_tsp_matrix


def test_registry():
    assert "onemax" in objectives.names()
    assert objectives.get("onemax") is onemax
    with pytest.raises(KeyError):
        objectives.get("nope")


def test_onemax():
    assert float(onemax(jnp.ones(10))) == pytest.approx(10.0)
    assert float(onemax_bits(jnp.array([0.9, 0.1, 0.5, 0.49]))) == 2.0


def test_sphere_rastrigin_ackley_optima():
    # genes = 0.5 → x = 0 → optimum 0 for all three
    mid = jnp.full((30,), 0.5)
    assert float(sphere(mid)) == pytest.approx(0.0, abs=1e-4)
    assert float(rastrigin(mid)) == pytest.approx(0.0, abs=1e-3)
    assert float(ackley(mid)) == pytest.approx(0.0, abs=1e-3)
    off = jnp.full((30,), 0.9)
    assert float(rastrigin(off)) < -1.0


def test_knapsack_reference_semantics():
    # Reference instance (test2/test.cu:22-26): feasible → value,
    # infeasible → capacity - weight.
    # counts decode as int(g*2): g=0.6 → 1 copy
    g = jnp.array([0.0, 0.0, 0.6, 0.6, 0.0, 0.0])  # item2 + item3: w=10 v=285
    assert float(default_knapsack(g)) == pytest.approx(285.0)
    g_over = jnp.array([0.6, 0.6, 0.6, 0.0, 0.0, 0.0])  # w=21 > 10
    assert float(default_knapsack(g_over)) == pytest.approx(10.0 - 21.0)


def test_knapsack_custom():
    kp = make_knapsack([10.0], [1.0], capacity=5.0, max_item_count=4)
    g = jnp.array([0.99])  # count 3
    assert float(kp(g)) == pytest.approx(30.0)


def test_tsp_reference_semantics():
    L = 4
    m = np.full((L, L), 100.0, dtype=np.float32)
    np.fill_diagonal(m, 0.0)
    m[0, 1] = m[1, 2] = m[2, 3] = 1.0
    tsp = make_tsp(m)
    tour = (jnp.arange(L) + 0.5) / L  # 0→1→2→3
    assert float(tsp(tour)) == pytest.approx(-3.0)
    # duplicated city → +10000 penalty per ordered pair (test3/test.cu:36-44)
    dup = jnp.array([0.5 / L, 0.5 / L, 2.5 / L, 3.5 / L])
    assert float(tsp(dup)) <= -(2 * 10_000)


def test_tsp_matrix_generator_plants_path():
    m = random_tsp_matrix(10, seed=0)
    assert m.shape == (10, 10)
    np.testing.assert_allclose(m[np.arange(9), np.arange(1, 10)], 10.0)
    assert np.all(np.diag(m) == 0.0)


def test_nk_landscape_properties(key):
    nk = make_nk_landscape(n=16, k=3, seed=0)
    g = jax.random.uniform(key, (16,))
    v = float(nk(g))
    assert 0.0 <= v <= 1.0
    # deterministic
    assert float(nk(g)) == v
    # flipping a bit changes fitness (epistasis wired up)
    g2 = g.at[0].set(1.0 - g[0])
    assert float(nk(g2)) != v


def test_deceptive_trap():
    trap = make_deceptive_trap(trap_size=5)
    all_ones = jnp.ones(20)
    all_zeros = jnp.zeros(20)
    assert float(trap(all_ones)) == pytest.approx(20.0)  # global optimum
    assert float(trap(all_zeros)) == pytest.approx(16.0)  # deceptive attractor
    # one block solved, rest zeros
    g = jnp.zeros(20).at[:5].set(1.0)
    assert float(trap(g)) == pytest.approx(5.0 + 12.0)


def test_objectives_vmap_and_jit(key):
    genomes = jax.random.uniform(key, (64, 30))
    for fn in [onemax, sphere, rastrigin, ackley, make_nk_landscape(30, 2),
               make_deceptive_trap(5)]:
        out = jax.jit(jax.vmap(fn))(genomes)
        assert out.shape == (64,)
        assert bool(jnp.all(jnp.isfinite(out)))


def test_kernel_rowwise_forms_match_per_genome(key):
    """Every objective carrying a ``kernel_rowwise`` batched form (the
    one the fused Pallas kernel actually evaluates) must agree with its
    per-genome form — including the factory-built NK / trap / knapsack
    forms added for in-kernel fused evaluation."""
    import jax

    from libpga_tpu import objectives
    from libpga_tpu.objectives import (
        default_knapsack,
        make_deceptive_trap,
        make_nk_landscape,
    )

    cases = [
        (objectives.onemax, 24),
        (objectives.onemax_bits, 24),
        (objectives.rastrigin, 30),
        (make_nk_landscape(24, 3, seed=1), 24),
        (make_deceptive_trap(5), 23),  # 23: exercises the unused tail
        (default_knapsack, 6),
    ]
    for obj, L in cases:
        rows = getattr(obj, "kernel_rowwise", None)
        assert rows is not None, obj
        g = jax.random.uniform(jax.random.fold_in(key, L), (17, L))
        a = np.asarray(jax.vmap(obj)(g))
        b = np.asarray(rows(g))
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-6)
        # Explicit-consts call form — what the fused kernel actually
        # executes (consts become kernel inputs, not closure copies).
        consts = tuple(getattr(obj, "kernel_rowwise_consts", ()))
        if consts:
            c = np.asarray(rows(g, *(jnp.asarray(x) for x in consts)))
            np.testing.assert_allclose(b, c, atol=0, rtol=0)
