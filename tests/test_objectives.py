"""Objective-function tests: known optima, reference-driver semantics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libpga_tpu import objectives
from libpga_tpu.objectives import (
    onemax,
    onemax_bits,
    sphere,
    rastrigin,
    ackley,
    default_knapsack,
    make_knapsack,
    make_tsp,
    make_nk_landscape,
    make_deceptive_trap,
)
from libpga_tpu.objectives.classic import random_tsp_matrix


def test_registry():
    assert "onemax" in objectives.names()
    assert objectives.get("onemax") is onemax
    with pytest.raises(KeyError):
        objectives.get("nope")


def test_onemax():
    assert float(onemax(jnp.ones(10))) == pytest.approx(10.0)
    assert float(onemax_bits(jnp.array([0.9, 0.1, 0.5, 0.49]))) == 2.0


def test_sphere_rastrigin_ackley_optima():
    # genes = 0.5 → x = 0 → optimum 0 for all three
    mid = jnp.full((30,), 0.5)
    assert float(sphere(mid)) == pytest.approx(0.0, abs=1e-4)
    assert float(rastrigin(mid)) == pytest.approx(0.0, abs=1e-3)
    assert float(ackley(mid)) == pytest.approx(0.0, abs=1e-3)
    off = jnp.full((30,), 0.9)
    assert float(rastrigin(off)) < -1.0


def test_knapsack_reference_semantics():
    # Reference instance (test2/test.cu:22-26): feasible → value,
    # infeasible → capacity - weight.
    # counts decode as int(g*2): g=0.6 → 1 copy
    g = jnp.array([0.0, 0.0, 0.6, 0.6, 0.0, 0.0])  # item2 + item3: w=10 v=285
    assert float(default_knapsack(g)) == pytest.approx(285.0)
    g_over = jnp.array([0.6, 0.6, 0.6, 0.0, 0.0, 0.0])  # w=21 > 10
    assert float(default_knapsack(g_over)) == pytest.approx(10.0 - 21.0)


def test_knapsack_custom():
    kp = make_knapsack([10.0], [1.0], capacity=5.0, max_item_count=4)
    g = jnp.array([0.99])  # count 3
    assert float(kp(g)) == pytest.approx(30.0)


def test_tsp_reference_semantics():
    L = 4
    m = np.full((L, L), 100.0, dtype=np.float32)
    np.fill_diagonal(m, 0.0)
    m[0, 1] = m[1, 2] = m[2, 3] = 1.0
    tsp = make_tsp(m)
    tour = (jnp.arange(L) + 0.5) / L  # 0→1→2→3
    assert float(tsp(tour)) == pytest.approx(-3.0)
    # duplicated city → +10000 penalty per ordered pair (test3/test.cu:36-44)
    dup = jnp.array([0.5 / L, 0.5 / L, 2.5 / L, 3.5 / L])
    assert float(tsp(dup)) <= -(2 * 10_000)


def test_tsp_matrix_generator_plants_path():
    m = random_tsp_matrix(10, seed=0)
    assert m.shape == (10, 10)
    np.testing.assert_allclose(m[np.arange(9), np.arange(1, 10)], 10.0)
    assert np.all(np.diag(m) == 0.0)


def test_nk_landscape_properties(key):
    nk = make_nk_landscape(n=16, k=3, seed=0)
    g = jax.random.uniform(key, (16,))
    v = float(nk(g))
    assert 0.0 <= v <= 1.0
    # deterministic
    assert float(nk(g)) == v
    # flipping a bit changes fitness (epistasis wired up)
    g2 = g.at[0].set(1.0 - g[0])
    assert float(nk(g2)) != v


def test_deceptive_trap():
    trap = make_deceptive_trap(trap_size=5)
    all_ones = jnp.ones(20)
    all_zeros = jnp.zeros(20)
    assert float(trap(all_ones)) == pytest.approx(20.0)  # global optimum
    assert float(trap(all_zeros)) == pytest.approx(16.0)  # deceptive attractor
    # one block solved, rest zeros
    g = jnp.zeros(20).at[:5].set(1.0)
    assert float(trap(g)) == pytest.approx(5.0 + 12.0)


def test_objectives_vmap_and_jit(key):
    genomes = jax.random.uniform(key, (64, 30))
    for fn in [onemax, sphere, rastrigin, ackley, make_nk_landscape(30, 2),
               make_deceptive_trap(5)]:
        out = jax.jit(jax.vmap(fn))(genomes)
        assert out.shape == (64,)
        assert bool(jnp.all(jnp.isfinite(out)))


def test_kernel_rowwise_forms_match_per_genome(key):
    """Every objective carrying a ``kernel_rowwise`` batched form (the
    one the fused Pallas kernel actually evaluates) must agree with its
    per-genome form — including the factory-built NK / trap / knapsack
    forms added for in-kernel fused evaluation."""
    import jax

    from libpga_tpu import objectives
    from libpga_tpu.objectives import (
        default_knapsack,
        make_deceptive_trap,
        make_nk_landscape,
    )

    cases = [
        (objectives.onemax, 24),
        (objectives.onemax_bits, 24),
        (objectives.rastrigin, 30),
        (make_nk_landscape(24, 3, seed=1), 24),
        (make_deceptive_trap(5), 23),  # 23: exercises the unused tail
        (default_knapsack, 6),
    ]
    for obj, L in cases:
        rows = getattr(obj, "kernel_rowwise", None)
        assert rows is not None, obj
        g = jax.random.uniform(jax.random.fold_in(key, L), (17, L))
        a = np.asarray(jax.vmap(obj)(g))
        b = np.asarray(rows(g))
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-6)
        # Explicit-consts call form — what the fused kernel actually
        # executes (consts become kernel inputs, not closure copies).
        consts = tuple(getattr(obj, "kernel_rowwise_consts", ()))
        if consts:
            c = np.asarray(rows(g, *(jnp.asarray(x) for x in consts)))
            np.testing.assert_allclose(b, c, atol=0, rtol=0)


# --------------------------------------------------- expression objectives


class TestExpressionObjectives:
    def test_arithmetic_matches_numpy(self):
        from libpga_tpu.objectives import from_expression

        g = np.random.default_rng(0).random((5, 12)).astype(np.float32)
        cases = [
            ("sum(g)", g.sum(axis=1)),
            ("mean(g * g)", (g * g).mean(axis=1)),
            ("-sum((g*10.24 - 5.12)**2)", -((g * 10.24 - 5.12) ** 2).sum(axis=1)),
            ("max(g) - min(g)", g.max(axis=1) - g.min(axis=1)),
            ("sum(min(g, 1 - g))", np.minimum(g, 1 - g).sum(axis=1)),
            ("sum(where(g >= 0.5, 1, 0))", (g >= 0.5).sum(axis=1)),
            ("sum(cos(2*pi*g))", np.cos(2 * np.pi * g).sum(axis=1)),
            ("sum(g % 0.25)", (g % 0.25).sum(axis=1)),
            ("sum(i * g) / L", (np.arange(12) * g).sum(axis=1) / 12.0),
            ("-(2**3) + sum(g)*0", np.full(5, -8.0)),
        ]
        for expr, want in cases:
            got = np.asarray(from_expression(expr).kernel_rowwise(jnp.asarray(g)))
            np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-5, err_msg=expr)

    def test_unary_minus_and_power_precedence(self):
        from libpga_tpu.objectives import from_expression

        g = np.full((1, 4), 0.5, np.float32)
        # -x**2 must parse as -(x**2), like Python
        got = float(from_expression("-sum(g)**2 + 0*sum(g)").kernel_rowwise(jnp.asarray(g))[0])
        assert got == -4.0

    def test_constants_scalar_and_vector(self):
        from libpga_tpu.objectives import from_expression

        g = np.random.default_rng(1).random((3, 6)).astype(np.float32)
        w = np.arange(6, dtype=np.float32)
        f = from_expression("dot(w, g) + c", w=w, c=2.0)
        got = np.asarray(f.kernel_rowwise(jnp.asarray(g)))
        np.testing.assert_allclose(got, (w * g).sum(axis=1) + 2.0, rtol=1e-5)
        # consts ride along as kernel inputs
        assert len(f.kernel_rowwise_consts) == 2

    def test_per_genome_form_matches_rowwise(self):
        from libpga_tpu.objectives import from_expression

        f = from_expression("sum(g*g)")
        g = np.random.default_rng(2).random(9).astype(np.float32)
        assert np.isclose(float(f(jnp.asarray(g))), float((g * g).sum()), rtol=1e-5)

    def test_errors(self):
        from libpga_tpu.objectives import ExpressionError, from_expression

        for bad in ("sum(", "sum(q)", "g * 2", "frobnicate(g)",
                    "sum(g,)", "where(g)", "1 ++", "sum(g) @ 2"):
            with pytest.raises(ExpressionError):
                from_expression(bad)
        with pytest.raises(ExpressionError):
            from_expression("dot(v, g)", v=np.ones((2, 2)))  # 2-D const
        with pytest.raises(ExpressionError):
            from_expression("sum(g) + sum", )  # name used as value
        with pytest.raises(ExpressionError):
            from_expression("dot(a, g) + dot(b, g)",
                            a=np.ones(3), b=np.ones(5))  # length clash
        with pytest.raises(ExpressionError):
            from_expression("sum(g)", where=np.ones(3))  # keyword shadow

    def test_engine_integration_and_vector_const_length(self):
        """An expression objective drives PGA end-to-end, and a vector
        constant fixes the probe genome length (docstring example)."""
        from libpga_tpu import PGA
        from libpga_tpu.objectives import from_expression

        L = 20
        w = np.linspace(1.0, 2.0, L).astype(np.float32)
        pga = PGA(seed=0)
        h = pga.create_population(256, L)
        pga.set_objective(from_expression("dot(w, g)", w=w))
        pga.run(25)
        _, best = pga.get_best_with_score(h)
        assert best > 0.8 * w.sum(), best

    def test_v2_roll_and_let_bindings(self):
        """``name = expr;`` statements and roll(x, k) — the circular
        neighbor shift: roll(x, k)[i] = x[(i+k) mod L]."""
        from libpga_tpu.objectives import from_expression

        g = np.random.default_rng(7).random((5, 12)).astype(np.float32)
        f = from_expression("a = roll(g, 1); b = roll(g, -2); sum(a*g + b)")
        want = (np.roll(g, -1, axis=1) * g + np.roll(g, 2, axis=1)).sum(1)
        np.testing.assert_allclose(
            np.asarray(f.kernel_rowwise(jnp.asarray(g))), want, rtol=1e-5
        )

    def test_v2_gather_shared_and_per_locus(self):
        from libpga_tpu.objectives import from_expression

        rng = np.random.default_rng(8)
        g = rng.random((6, 10)).astype(np.float32)
        t = rng.random(7).astype(np.float32)
        f = from_expression("sum(gather(t, g * 7))", t=t)
        idx = np.clip(np.floor(g * 7), 0, 6).astype(int)
        np.testing.assert_allclose(
            np.asarray(f.kernel_rowwise(jnp.asarray(g))),
            t[idx].sum(1), rtol=1e-5,
        )
        assert f.pinned_genome_len is None  # a table's n is not L
        t2 = rng.random((4, 10)).astype(np.float32)  # per-locus (n, L)
        f2 = from_expression("sum(gather(T, g * 4))", T=t2)
        idx2 = np.clip(np.floor(g * 4), 0, 3).astype(int)
        want = t2[idx2, np.arange(10)[None, :]].sum(1)
        np.testing.assert_allclose(
            np.asarray(f2.kernel_rowwise(jnp.asarray(g))), want, rtol=1e-5
        )
        assert f2.pinned_genome_len == 10  # per-locus width IS L

    def test_v2_gather_table_kind_follows_registered_rank(self):
        """A (1, L) matrix registered as 2-D is a PER-LOCUS table (one
        entry row), not a shared L-entry table — the runtime shapes are
        identical, so the registered rank must decide (review finding).
        And a per-locus table whose width disagrees with the genome is
        a shape error, not silent shared-table semantics."""
        from libpga_tpu.objectives import ExpressionError, from_expression

        t = np.arange(10, dtype=np.float32).reshape(1, 10)
        f = from_expression("sum(gather(T, g * 1))", T=t)
        g = np.zeros((3, 10), dtype=np.float32)  # all indices clip to 0
        np.testing.assert_allclose(
            np.asarray(f.kernel_rowwise(jnp.asarray(g))),
            np.full(3, t.sum()),  # row 0 broadcast across loci
        )
        assert f.pinned_genome_len == 10
        with pytest.raises(ExpressionError, match="width"):
            # (5, 1) per-locus table pins L=1; probing at L=1 works but
            # an L=8 population must be rejected loudly
            f2 = from_expression(
                "sum(gather(T2, g * 5))",
                T2=np.arange(5, dtype=np.float32).reshape(5, 1),
            )
            f2.kernel_rowwise(jnp.zeros((2, 8), dtype=np.float32))

    def test_v2_nk_landscape_expression_matches_builtin(self):
        """The reference-style NK form is expressible (verdict round-4
        item 4): codes from rolled bit vectors, per-locus table lookup —
        identical scores to make_nk_landscape."""
        from libpga_tpu.objectives import from_expression
        from libpga_tpu.objectives.classic import make_nk_landscape

        n, k = 16, 3
        nk = make_nk_landscape(n, k, seed=3)
        tab_t = np.asarray(nk.kernel_rowwise_consts[0])
        f = from_expression(
            "b = g >= 0.5;"
            "codes = b + 2*roll(b, 1) + 4*roll(b, 2) + 8*roll(b, 3);"
            "mean(gather(T, codes))",
            T=tab_t,
        )
        g = np.random.default_rng(1).random((16, n)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(f.kernel_rowwise(jnp.asarray(g))),
            np.asarray(jax.vmap(nk)(jnp.asarray(g))),
            rtol=1e-5, atol=1e-6,
        )

    def test_v2_tour_cost_expression_matches_tsp_coords(self):
        """A Euclidean TSP tour cost is expressible: coordinate gathers
        + adjacency via roll + open-path masking on ``i``. Matches
        make_tsp_coords on duplicate-free tours (the expression carries
        no duplicate penalty; the permutation operators keep tours
        valid)."""
        from libpga_tpu.objectives import from_expression
        from libpga_tpu.objectives.classic import (
            make_tsp_coords, random_tsp_coords,
        )

        C = 24
        coords = random_tsp_coords(C, seed=2)
        f = from_expression(
            "c = floor(g * L);"
            "x = gather(X, c); y = gather(Y, c);"
            "dx = roll(x, 1) - x; dy = roll(y, 1) - y;"
            "-sum(where(i < L - 1, sqrt(dx*dx + dy*dy + 1e-12), 0))",
            X=coords[:, 0], Y=coords[:, 1],
        )
        tsp = make_tsp_coords(coords)
        rng = np.random.default_rng(5)
        perms = np.stack([rng.permutation(C) for _ in range(8)])
        g = ((perms + 0.5) / C).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(f.kernel_rowwise(jnp.asarray(g))),
            np.asarray(jax.vmap(tsp)(jnp.asarray(g))),
            rtol=1e-4,
        )

    def test_v2_errors(self):
        from libpga_tpu.objectives import ExpressionError, from_expression

        with pytest.raises(ExpressionError, match="rebound"):
            from_expression("x = g; x = g; sum(x)")
        with pytest.raises(ExpressionError, match="builtin name"):
            from_expression("g = sum(g); g")
        with pytest.raises(ExpressionError, match="integer literal"):
            from_expression("sum(roll(g, L))")
        with pytest.raises(ExpressionError, match="registered constant"):
            from_expression("sum(gather(g, g))")
        with pytest.raises(ExpressionError, match="only be used as"):
            from_expression("sum(T * g)", T=np.ones((3, 4)))
        with pytest.raises(ExpressionError, match="caps at 512"):
            from_expression("sum(gather(t, g))", t=np.ones(600))
        # folded literal shifts are fine
        from_expression("sum(roll(g, 2 + 1))")

    def test_v2_fuses_into_pallas_kernel(self):
        """roll + gather + let-bindings lower inside the breed kernel
        (interpret mode; hardware lowering in tools/tpu_kernel_checks)."""
        from jax.experimental.pallas import tpu as pltpu

        from libpga_tpu.objectives import from_expression
        from libpga_tpu.objectives.classic import make_nk_landscape
        from libpga_tpu.ops.pallas_step import make_pallas_breed

        n = 16
        nk = make_nk_landscape(n, 3, seed=3)
        tab_t = np.asarray(nk.kernel_rowwise_consts[0])
        f = from_expression(
            "b = g >= 0.5;"
            "codes = b + 2*roll(b, 1) + 4*roll(b, 2) + 8*roll(b, 3);"
            "mean(gather(T, codes))",
            T=tab_t,
        )
        g = np.random.default_rng(3).random((256, n)).astype(np.float32)
        s = f.kernel_rowwise(jnp.asarray(g))
        with pltpu.force_tpu_interpret_mode():
            breed = make_pallas_breed(
                256, n, deme_size=128,
                fused_obj=f.kernel_rowwise,
                fused_consts=f.kernel_rowwise_consts,
            )
            g2, s2 = breed(jnp.asarray(g), s, jax.random.key(0))
        np.testing.assert_allclose(
            np.asarray(s2),
            np.asarray(f.kernel_rowwise(jnp.asarray(g2))),
            rtol=1e-4, atol=1e-4,
        )

    def test_fuses_into_pallas_kernel(self):
        """The compiled rowwise form lowers inside the breed kernel
        (interpret mode), consts arriving as kernel inputs."""
        from jax.experimental.pallas import tpu as pltpu

        from libpga_tpu.objectives import from_expression
        from libpga_tpu.ops.pallas_step import make_pallas_breed

        L = 16
        w = np.linspace(0.5, 1.5, L).astype(np.float32)
        f = from_expression("dot(w, g)", w=w)
        g = np.random.default_rng(3).random((256, L)).astype(np.float32)
        s = (w * g).sum(axis=1)
        with pltpu.force_tpu_interpret_mode():
            breed = make_pallas_breed(
                256, L, deme_size=128,
                fused_obj=f.kernel_rowwise,
                fused_consts=f.kernel_rowwise_consts,
            )
            g2, s2 = breed(jnp.asarray(g), jnp.asarray(s), jax.random.key(0))
        np.testing.assert_allclose(
            np.asarray(s2), (w * np.asarray(g2)).sum(axis=1),
            rtol=1e-4, atol=1e-4,
        )
