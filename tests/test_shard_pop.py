"""Population sharding (``parallel/shard_pop.py``, ISSUE 7).

Five property families, all CPU-runnable on the simulated 8-device
harness (conftest forces ``xla_force_host_platform_device_count=8``):

1. **Admissibility** — the S² | P gate, ValueError naming the valid
   shard counts (the round-8 ablate-flag convention), config
   validation.
2. **Mixing algebra** — the per-generation global permutation is a
   bijection whose slab hops one shard with the u·D+d comb interleave,
   and a lineage BFS over (within-shard panmictic breeding + the slab
   edges) reaches every shard in <= S generations: no closed
   super-blocks at any admissible S.
3. **Structural purity** — ``pop_shards=1`` lowers to the
   byte-identical StableHLO of the pre-sharding run loop, and the
   S>1 while body contains EXACTLY one cross-shard collective pair
   (one ppermute + one all_gather of S·k scalars) and nothing else.
4. **Panmictic equivalence** — 2/4/8-shard runs reach the
   bit-identical final best as the single-shard same-seed run for a
   rank-selection config, global elitism never loses the best, the
   telemetry history carries the GLOBAL best, and the cohort-dynamics
   simulation's sharded takeover completes within the band.
5. **Integration** — shard_sync event schema, engine caching, target
   early-stop, checkpoint save@4 → restore@2 as one logical array.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from libpga_tpu import PGA, PGAConfig, TelemetryConfig
from libpga_tpu.parallel import shard_pop as sp


pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device CPU harness"
)


def _solver(S, *, seed=7, pop=256, length=32, tel=None, **cfg):
    cfg.setdefault("selection", "truncation")
    cfg.setdefault("mutation_rate", 0.05)
    cfg.setdefault("use_pallas", False)
    pga = PGA(
        seed=seed,
        config=PGAConfig(pop_shards=S, telemetry=tel, **cfg),
    )
    h = pga.create_population(pop, length)
    pga.set_objective("onemax_bits")
    return pga, h


# -------------------------------------------------------------- admissibility


def test_admissible_shards_is_the_s_squared_divisor_set():
    assert sp.admissible_shards(256, 8) == [1, 2, 4, 8]
    assert sp.admissible_shards(100, 8) == [1, 2, 5]  # 4, 25 | 100
    assert sp.admissible_shards(96, 8) == [1, 2, 4]
    assert sp.admissible_shards(7, 8) == [1]


def test_validate_shards_names_the_valid_counts():
    with pytest.raises(ValueError) as e:
        sp.validate_shards(100, 4, 8)
    msg = str(e.value)
    assert "pop_shards=4" in msg and "[1, 2, 5]" in msg
    sp.validate_shards(256, 8, 8)  # admissible: no raise


def test_inadmissible_pop_shards_raises_at_run():
    pga, h = _solver(4, pop=100)
    with pytest.raises(ValueError, match="valid shard counts"):
        pga.run(2)


def test_config_rejects_nonpositive_pop_shards():
    with pytest.raises(ValueError, match="pop_shards"):
        PGAConfig(pop_shards=0)


def test_unknown_ablate_flag_raises_naming_valid_set():
    with pytest.raises(ValueError, match=r"sync.*mix|mix.*sync"):
        sp.make_sharded_run(
            lambda g: jnp.sum(g, axis=-1), lambda *a: (a[0], None),
            256, 16, 2, ablate=("warp",),
        )


# ------------------------------------------------------------- mixing algebra


@pytest.mark.parametrize("S", [2, 4, 8])
def test_mix_perm_is_a_permutation_with_comb_interleave(S):
    P = 64 * S * S
    perm = sp.shard_mix_perm(P, S)
    assert sorted(perm) == list(range(P))  # bijection — nothing lost
    Ps, mix = P // S, sp.mix_rows(P, S)
    ileave = sp.comb_interleave_rows(mix)
    inv = np.argsort(ileave)
    for s in range(S):
        nxt = (s + 1) % S
        for m in range(mix):
            # the stride-S comb hops one shard, landing at the
            # u·D+d-interleaved comb slot (the round-8 cross-deme
            # write interleave, one level up)
            assert perm[s * Ps + m * S] == nxt * Ps + inv[m] * S
        # off-comb rows stay put
        for j in range(Ps):
            if j % S != 0:
                assert perm[s * Ps + j] == s * Ps + j
    # every deme group of the in-shard layout contributes comb rows:
    # the comb's row set {m·S} intersects every W-row group for any
    # group width W >= S (here: the migrating set is uniform stride S).
    comb_rows = {m * S for m in range(mix)}
    assert max(np.diff(sorted(comb_rows))) == S


@pytest.mark.parametrize("S", [2, 4, 8])
def test_lineage_reaches_every_shard_no_closed_superblocks(S):
    """BFS over one generation's lineage edges: a child anywhere in a
    shard descends from ANY row of that shard (local selection is
    panmictic within the shard), then the mix permutation moves the
    slab. Every shard must be reachable from shard 0 within S
    generations — the no-disconnected-super-blocks property that
    killed the naive read==write ping-pong in round 8."""
    P = 16 * S * S
    perm = sp.shard_mix_perm(P, S)
    Ps = P // S
    shard_of = lambda row: row // Ps
    reach = {0}
    for _ in range(S):
        nxt = set(reach)
        for s in reach:
            # children of shard s land in shard s (non-slab) and in
            # shard_of(perm[slab rows])
            for j in range(Ps):
                nxt.add(shard_of(perm[s * Ps + j]))
        reach = nxt
        if len(reach) == S:
            break
    assert len(reach) == S, f"closed super-block: only {sorted(reach)}"


def test_comb_interleave_rows_is_slab_permutation():
    for mix in (1, 4, 8, 16, 48):
        ileave = sp.comb_interleave_rows(mix)
        assert sorted(ileave) == list(range(mix))


# ---------------------------------------------------------- structural purity


def test_pop_shards_one_lowering_is_unchanged():
    """pop_shards=1 (the default) must lower to the byte-identical
    StableHLO of the pre-sharding run loop — the same gate telemetry
    and fallback already pass (the reference loop is replicated
    verbatim below, compared through ``analysis.fingerprint`` as in
    tests/test_telemetry.py)."""
    from libpga_tpu.analysis import canonical_text, fingerprint
    from libpga_tpu.ops.evaluate import evaluate as _evaluate

    pga, h = _solver(1, selection="tournament")
    pop = pga.population(h)
    args = (
        pop.genomes, jax.random.key(0), jnp.int32(3),
        jnp.float32(jnp.inf), pga._mutate_params(),
    )
    sharded_off = pga._compiled_run(pop.size, pop.genome_len)

    obj = pga._objective
    breed = pga._breed_fn()

    def run_loop(genomes, key, n, target, mparams):
        del mparams
        scores0 = _evaluate(obj, genomes)

        def cond(carry):
            g, s, k, gen = carry
            return jnp.logical_and(gen < n, jnp.max(s) < target)

        def body(carry):
            g, s, k, gen = carry
            k, sub = jax.random.split(k)
            g2 = breed(g, s, sub)
            s2 = _evaluate(obj, g2)
            return (g2, s2, k, gen + 1)

        init = (genomes, scores0, key, jnp.int32(0))
        g, s, k, gens_done = jax.lax.while_loop(cond, body, init)
        return g, s, gens_done

    assert fingerprint(sharded_off, *args) == fingerprint(
        run_loop, *args, donate_argnums=(0,)
    )
    # and no cross-shard machinery leaked into the unsharded program
    text = canonical_text(sharded_off, *args)
    assert "ppermute" not in text and "all-gather" not in text


def test_exactly_one_collective_pair_per_generation():
    """The ISSUE 7 cost model, asserted on the jaxpr through the shared
    auditor: the S>1 while BODY (= one generation) contains exactly one
    ppermute (the comb slab) and one all_gather (the S·k
    rank-threshold sketch) — and no other cross-shard collective of
    any kind (``analysis.collective_budget`` checks the full
    collective vocabulary, not just the five the old hand-rolled scan
    listed)."""
    from libpga_tpu.analysis import IRContractError, collective_budget

    pga, h = _solver(4)
    fn = pga._compiled_sharded_run(256, 32)
    assert fn.k_sync * fn.shards == 4  # S·k scalars (elitism 0 -> k=1)
    pop = pga.population(h)
    keys = jax.random.split(jax.random.key(0), 4)
    args = (
        pop.genomes, keys, jnp.int32(3), jnp.float32(jnp.inf),
        pga._mutate_params(),
    )
    counts = collective_budget(
        fn.jitted, *args, ppermute=1, all_gather=1
    )
    assert counts.get("ppermute") == 1 and counts.get("all_gather") == 1
    # the budget is a real gate: demanding a second ppermute must fail
    with pytest.raises(IRContractError, match="ppermute"):
        collective_budget(fn.jitted, *args, ppermute=2, all_gather=1)


# ------------------------------------------------------ panmictic equivalence


def test_sharded_final_best_bit_identical_across_shard_matrix():
    """2/4/8-shard CPU runs of a rank-selection config reach the
    BIT-IDENTICAL final best as the single-shard same-seed run: the
    identical optimum score (f32-exact) and an optimal phenotype —
    sharded mixing must not break convergence at any admissible S."""
    def final_best(S):
        pga, h = _solver(S, elitism=2)
        gens = pga.run(400, target=32.0)
        g, s = pga.get_best_with_score(h)
        return gens, g, np.float32(s)

    gens1, g1, s1 = final_best(1)
    assert gens1 < 400 and s1 == np.float32(32.0)
    assert (g1 >= 0.5).all()
    for S in (2, 4, 8):
        gensS, gS, sS = final_best(S)
        assert gensS < 400, f"S={S} never reached the optimum"
        assert sS.tobytes() == s1.tobytes(), f"S={S}: {sS} != {s1}"
        assert (gS >= 0.5).all(), f"S={S} best genome not optimal"


def test_sharded_elitism_never_loses_the_global_best():
    """Global rank-threshold elitism: the history's best column must be
    non-decreasing (the global top-1 always survives somewhere)."""
    pga, h = _solver(
        4, elitism=1, tel=TelemetryConfig(history_gens=64),
    )
    pga.run(30)
    best = pga.history(h).best
    assert len(best) == 30
    assert (np.diff(best) >= 0).all(), best


def test_sharded_history_carries_the_global_best():
    pga, h = _solver(4, tel=TelemetryConfig(history_gens=64))
    pga.run(12)
    hist = pga.history(h)
    assert len(hist) == 12
    assert np.isfinite(hist.mean).all() and np.isfinite(hist.std).all()
    installed = float(jnp.max(pga.population(h).scores))
    assert abs(float(hist.best[-1]) - installed) < 1e-6


def test_sharded_takeover_simulation_within_band():
    """The selection_equivalence cohort machinery extended over shards:
    takeover must COMPLETE (no closed super-blocks) and stay within
    12% of panmictic at this reduced test size (the full-size tool run
    holds the 1.2% acceptance band — small populations are noisier)."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "selection_equivalence",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "selection_equivalence.py",
        ),
    )
    se = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(se)
    cap = 300
    pan = np.mean([
        se._sim_takeover("panmictic", 20 + s, pop=1 << 13, cap=cap)
        for s in range(3)
    ])
    for S in (2, 4):
        sh = np.mean([
            se._sim_takeover(
                "sharded", 20 + s, pop=1 << 13, cap=cap, shards=S
            )
            for s in range(3)
        ])
        assert sh < cap, f"S={S}: takeover never completed (disconnected)"
        assert abs(sh / pan - 1.0) < 0.12, (S, sh, pan)


def test_simulate_rejects_inadmissible_shards():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "selection_equivalence",
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "selection_equivalence.py",
        ),
    )
    se = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(se)
    with pytest.raises(ValueError, match="valid shard counts"):
        se._sim_layout("sharded", 512, pop=1000, shards=7)


# ---------------------------------------------------------------- integration


def test_sharded_run_respects_target_and_gens():
    pga, h = _solver(4, elitism=1)
    gens = pga.run(400, target=32.0)
    assert gens < 400
    assert float(pga.get_best_with_score(h)[1]) == 32.0
    pga2, h2 = _solver(4)
    assert pga2.run(9) == 9


def test_sharded_run_installs_one_logical_population():
    pga, h = _solver(2, pop=128, length=16)
    pga.run(5)
    pop = pga.population(h)
    assert pop.genomes.shape == (128, 16)
    assert pop.scores.shape == (128,)
    # installed scores describe the installed genomes (oracle check)
    expected = np.asarray(
        jnp.sum((pop.genomes >= 0.5).astype(jnp.float32), axis=1)
    )
    assert np.allclose(np.asarray(pop.scores), expected)


def test_shard_sync_event_is_schema_valid(tmp_path):
    from libpga_tpu.utils import telemetry

    path = str(tmp_path / "events.jsonl")
    pga, h = _solver(
        4, tel=TelemetryConfig(history_gens=8, events_path=path),
    )
    pga.run(3)
    records = telemetry.validate_log(path)  # raises on schema violation
    sync = [r for r in records if r["event"] == "shard_sync"]
    assert len(sync) == 1
    assert sync[0]["shards"] == 4
    assert sync[0]["mix_rows"] == 256 // 4 // 4


def test_sharded_compilation_is_cached_across_runs():
    pga, h = _solver(4)
    pga.run(3)
    n_compiled = len(pga._compiled)
    pga.run(3)
    assert len(pga._compiled) == n_compiled


def test_checkpoint_roundtrip_save_at_4_restore_at_2(tmp_path):
    """A sharded population checkpoints as ONE logical array (the
    resize path's contract): save under pop_shards=4, restore into a
    pop_shards=2 engine, best preserved exactly, evolution continues."""
    from libpga_tpu.utils import checkpoint

    path = str(tmp_path / "state.npz")
    pga, h = _solver(4, elitism=1)
    pga.run(10)
    best_before = float(pga.get_best_with_score(h)[1])
    checkpoint.save(pga, path)

    pga2 = PGA(
        seed=99,
        config=PGAConfig(
            pop_shards=2, selection="truncation", mutation_rate=0.05,
            use_pallas=False, elitism=1,
        ),
    )
    checkpoint.restore(pga2, path)
    h2 = pga2._handles()[0]
    pga2.set_objective("onemax_bits")
    assert float(pga2.get_best_with_score(h2)[1]) == best_before
    pga2.run(10)
    assert float(pga2.get_best_with_score(h2)[1]) >= best_before


def test_capi_bridge_set_pop_shards():
    """The C ABI's pga_set_pop_shards bridge: installs the config
    field, validates the range, and a sharded run through the bridge
    handle works end to end."""
    from libpga_tpu import capi_bridge as cb

    handle = cb.init(7)
    try:
        with pytest.raises(ValueError):
            cb.set_pop_shards(handle, 0)
        cb.set_pop_shards(handle, 2)
        assert cb._solver(handle).config.pop_shards == 2
        pop = cb.create_population(handle, 64, 16, 0)
        cb.set_objective_name(handle, "onemax_bits")
        solver = cb._solver(handle)
        assert solver.run(3) == 3
        cb.set_pop_shards(handle, 1)
        assert cb._solver(handle).config.pop_shards == 1
    finally:
        cb.deinit(handle)


def test_serving_signature_separates_shard_counts():
    """ISSUE 7 satellite: sharded and unsharded runs must never share
    a compiled serving program — pop_shards is part of the bucket
    signature tuple (and therefore of the cache.py program key, which
    extends the signature)."""
    from libpga_tpu.serving import BatchedRuns, RunRequest

    req = RunRequest(size=256, genome_len=16, n=2, seed=0)
    ex1 = BatchedRuns("onemax", config=PGAConfig(use_pallas=False))
    ex2 = BatchedRuns(
        "onemax", config=PGAConfig(use_pallas=False, pop_shards=4)
    )
    assert ex1.signature(req) != ex2.signature(req)
