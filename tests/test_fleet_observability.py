"""Fleet-wide observability (ISSUE 9): cross-process trace
propagation, merged metric snapshots, straggler detection, and the
fleet_top console.

Most tests here are process-free — synthetic spools, synthetic
registries — because the properties under test are the MERGE and
REFUSAL semantics (torn files, version mismatches, concurrent
flushes) and the console's rendering, none of which need a live
fleet. The one real-process test pins the end-to-end trace contract:
span monotonicity (submit <= claim <= execute <= publish <= done)
and breakdown coverage (spans tile >= 95% of measured e2e). The kill
-9 "trace shows both attempts" property rides on the existing
process tests in test_fleet.py; the 8-process matrix is
tools/fleet_smoke.py (CI stage 9) + the tracing gates of CI stage 10.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from libpga_tpu import PGAConfig
from libpga_tpu.config import FleetConfig
from libpga_tpu.serving.fleet import (
    METRICS_FILE_SCHEMA,
    Fleet,
    FleetTicket,
    Spool,
    fleet_status,
    load_spool_metrics,
    merge_spool_metrics,
    write_metrics_file,
)
from libpga_tpu.utils import metrics as M
from libpga_tpu.utils import telemetry as T

CFG = PGAConfig(use_pallas=False)


def make_registry(execute_ms=(), published=0):
    """A worker-like registry: execute-latency observations + the
    published-tickets counter."""
    reg = M.MetricsRegistry()
    for v in execute_ms:
        reg.histogram("serving.ticket.execute_ms").observe(v)
    if published:
        reg.counter("worker.tickets.published").bump(published)
    return reg


# ----------------------------------------------------------- merge algebra


def test_merge_snapshots_proc_labels_and_aggregate():
    a = make_registry(execute_ms=[10.0, 20.0], published=2)
    b = make_registry(execute_ms=[30.0], published=1)
    merged = M.merge_snapshots(
        [("w0", a.snapshot()), ("w1", b.snapshot())]
    )
    assert merged["merged_from"] == ["w0", "w1"]
    # every per-proc series is labeled with its origin
    counters = {
        (r["name"], r["labels"].get("proc")): r["value"]
        for r in merged["counters"]
    }
    assert counters[("worker.tickets.published", "w0")] == 2
    assert counters[("worker.tickets.published", "w1")] == 1
    # histograms additionally fold into ONE aggregate without the proc
    # label, merged through HistogramSnapshot.merge
    hists = [
        r for r in merged["histograms"]
        if r["name"] == "serving.ticket.execute_ms"
    ]
    per_proc = [r for r in hists if "proc" in r["labels"]]
    agg = [r for r in hists if "proc" not in r["labels"]]
    assert len(per_proc) == 2 and len(agg) == 1
    assert agg[0]["count"] == 3
    assert agg[0]["sum"] == pytest.approx(60.0)
    # merge is order-independent (associative + commutative folding)
    swapped = M.merge_snapshots(
        [("w1", b.snapshot()), ("w0", a.snapshot())]
    )
    agg2 = [
        r for r in swapped["histograms"]
        if r["name"] == "serving.ticket.execute_ms"
        and "proc" not in r["labels"]
    ]
    assert agg2[0]["counts"] == agg[0]["counts"]
    # and the whole merged snapshot renders to a lint-clean exposition
    assert M.lint_prometheus(M.prometheus_text(merged)) == []


def test_merge_snapshots_refuses_schema_mismatch_and_duplicates():
    snap = make_registry(execute_ms=[1.0]).snapshot()
    bad = dict(snap, schema=99)
    with pytest.raises(ValueError, match="refusing to merge"):
        M.merge_snapshots([("w0", snap), ("w1", bad)])
    with pytest.raises(ValueError, match="duplicate"):
        M.merge_snapshots([("w0", snap), ("w0", snap)])


# ------------------------------------------------------- spool snapshots


def test_spool_metrics_torn_file_skipped_version_mismatch_refused(tmp_path):
    spool = Spool(str(tmp_path))
    write_metrics_file(spool, "w0", make_registry([5.0]).snapshot())
    # torn file: a crash mid-write of a NON-atomic writer (the real
    # flusher renames atomically — this bare write DELIBERATELY
    # violates the spool discipline to exercise the reader's
    # torn-file defense)
    with open(spool.metrics_path("w1"), "w") as fh:  # pga-lint: disable=spool-atomic-write
        fh.write('{"schema_version": 1, "proc": "w1", "snapsho')
    payloads, skipped = load_spool_metrics(spool)
    assert [p["proc"] for p in payloads] == ["w0"]
    assert skipped == ["w1.json"]
    merged = merge_spool_metrics(spool)
    assert merged["merged_from"] == ["w0"]
    assert merged["skipped_files"] == ["w1.json"]
    # a PARSEABLE file from another schema version refuses loudly
    Spool.write_json(
        spool.metrics_path("w2"),
        {"schema_version": METRICS_FILE_SCHEMA + 1, "proc": "w2",
         "snapshot": make_registry().snapshot()},
    )
    with pytest.raises(ValueError, match="schema_version"):
        load_spool_metrics(spool)
    with pytest.raises(ValueError, match="schema_version"):
        merge_spool_metrics(spool)


def test_kill_mid_flush_leaves_previous_valid_file(tmp_path):
    """The atomic-rename discipline: a writer that dies mid-flush (temp
    file written, rename never happened) leaves the PREVIOUS snapshot
    intact and the temp file invisible to the loader."""
    spool = Spool(str(tmp_path))
    write_metrics_file(spool, "w0", make_registry([1.0]).snapshot())
    # simulate the kill: the next flush got as far as the temp file
    tmp = f"{spool.metrics_path('w0')}.{os.getpid()}.tmp"
    with open(tmp, "w") as fh:
        fh.write('{"schema_version": 1, "proc": "w0", "snap')  # torn
    payloads, skipped = load_spool_metrics(spool)
    assert len(payloads) == 1 and skipped == []
    assert payloads[0]["snapshot"]["histograms"][0]["count"] == 1


def test_merge_under_concurrent_flushes(tmp_path):
    """Writers hammering the spool while a reader merges: every merge
    sees a consistent (atomic-rename) file set — no torn reads, and
    the final merge carries every writer's last flush."""
    spool = Spool(str(tmp_path))
    stop = threading.Event()
    errors = []

    def writer(wid):
        i = 0
        while not stop.is_set():
            i += 1
            reg = make_registry(execute_ms=[float(i)] * i, published=i)
            try:
                write_metrics_file(spool, wid, reg.snapshot())
            except Exception as e:  # pragma: no cover - diagnostic
                errors.append(e)

    threads = [
        threading.Thread(target=writer, args=(f"w{i}",)) for i in range(3)
    ]
    for t in threads:
        t.start()
    merges = 0
    deadline = time.monotonic() + 1.0
    try:
        while time.monotonic() < deadline:
            merged = merge_spool_metrics(spool)
            assert M.lint_prometheus(M.prometheus_text(merged)) == []
            merges += 1
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not errors
    assert merges >= 3
    final = merge_spool_metrics(spool)
    assert sorted(final["merged_from"]) == ["w0", "w1", "w2"]


# ------------------------------------------------------------ span logs


def test_trace_roundtrip_torn_tail_and_version_refusal(tmp_path):
    path = str(tmp_path / "b1.trace.jsonl")
    r1 = T.trace_span_record("claim", 1.0, 2.0, worker="w0", batch="b1")
    r2 = T.trace_span_record("execute", 2.0, 5.0, worker="w0", batch="b1")
    T.append_trace(path, r1)
    T.append_trace(path, r2)
    with open(path, "a") as fh:
        fh.write('{"schema": 1, "event": "trace_span", "span": "pub')
    recs = T.read_trace(path)  # torn LAST line dropped silently
    assert [r["span"] for r in recs] == ["claim", "execute"]
    for r in recs:
        T.validate_event(r)
    assert T.span_ms(recs[1]) == pytest.approx(3000.0)
    # a record from another trace schema refuses loudly
    with open(path, "w") as fh:
        fh.write(json.dumps(dict(r1, trace_schema=99)) + "\n")
    with pytest.raises(ValueError, match="span-log schema"):
        T.read_trace(path)
    # a torn MIDDLE line is corruption, not a benign tail
    with open(path, "w") as fh:
        fh.write('{"torn\n' + json.dumps(r1) + "\n")
    with pytest.raises(ValueError, match="torn span-log line"):
        T.read_trace(path)


def test_anchored_wall_tracks_monotonic_deltas():
    a = T.anchored_wall()
    m = time.monotonic()
    b = T.anchored_wall(m)
    assert b >= a
    assert T.anchored_wall(m + 1.0) - b == pytest.approx(1.0)


# ----------------------------------------------------- straggler scanning


def test_straggler_detection_flags_slow_worker(tmp_path):
    fleet = Fleet(
        str(tmp_path), "onemax", config=CFG,
        fleet=FleetConfig(
            n_workers=1, straggler_factor=2.0, straggler_min_samples=4,
        ),
        registry=M.MetricsRegistry(),
    )
    for wid, ms in (("w0", 10.0), ("w1", 10.0), ("w2", 500.0)):
        write_metrics_file(
            fleet.spool, wid, make_registry([ms] * 5).snapshot()
        )
    alerts = fleet.detect_stragglers()
    assert [a["worker"] for a in alerts] == ["w2"]
    assert alerts[0]["p95_ms"] > alerts[0]["fleet_p95_ms"]
    T.validate_event({
        "schema": T.EVENT_SCHEMA_VERSION, "ts": 0.0,
        "event": "straggler_alert", **alerts[0],
    })
    health = fleet.registry.gauge("fleet.worker.health", worker="w2")
    assert health.value == 0.0
    assert fleet.registry.gauge(
        "fleet.worker.health", worker="w0"
    ).value == 1.0
    # alerts fire on the TRANSITION: a second scan stays quiet
    assert fleet.detect_stragglers() == []
    # recovery restores the gauge (and re-arms the alert)
    write_metrics_file(
        fleet.spool, "w2", make_registry([10.0] * 5).snapshot()
    )
    assert fleet.detect_stragglers() == []
    assert health.value == 1.0


def test_straggler_needs_samples_and_peers(tmp_path):
    fleet = Fleet(
        str(tmp_path), "onemax", config=CFG,
        fleet=FleetConfig(n_workers=1, straggler_min_samples=10),
        registry=M.MetricsRegistry(),
    )
    # one worker only: no fleet median to compare against
    write_metrics_file(
        fleet.spool, "w0", make_registry([900.0] * 20).snapshot()
    )
    assert fleet.detect_stragglers() == []
    # a second worker below min_samples stays out of the scan
    write_metrics_file(
        fleet.spool, "w1", make_registry([1.0] * 3).snapshot()
    )
    assert fleet.detect_stragglers() == []


# ------------------------------------------------- status + fleet_top


def synthetic_spool(tmp_path):
    """A dead fleet's spool: one pending batch, one claimed batch with
    a lease, one dead batch, two worker metric flushes + a coordinator
    flush, and a span log."""
    spool = Spool(str(tmp_path / "spool"))
    Spool.write_json(spool.path("pending", "b1.json"), {
        "batch": "b1.json", "formed_at": T.anchored_wall() - 3.0,
        "trace": True, "attempts": [],
        "tickets": [{"tid": "t1"}, {"tid": "t2"}],
    })
    Spool.write_json(spool.path("claimed", "b2.json"), {
        "batch": "b2.json", "formed_at": T.anchored_wall() - 9.0,
        "trace": True, "attempts": ["w9"], "tickets": [{"tid": "t3"}],
    })
    Spool.write_json(spool.lease_path("b2.json"),
                     {"worker": "w0", "pid": 1})
    Spool.write_json(spool.path("dead", "b0.json"),
                     {"batch": "b0.json", "tickets": []})
    Spool.write_json(spool.path("results", "t9.json"), {"tid": "t9"})
    write_metrics_file(
        spool, "w0", make_registry([12.0] * 6, published=6).snapshot(),
        batches_done=3,
    )
    write_metrics_file(
        spool, "w1", make_registry([15.0] * 4, published=4).snapshot(),
        batches_done=2, pid=999_999_999,  # definitely not alive
    )
    coord = M.MetricsRegistry()
    coord.histogram("fleet.ticket.e2e_ms").observe(120.0)
    coord.histogram("fleet.ticket.e2e_ms").observe(180.0)
    coord.histogram("fleet.ticket.spool_wait_ms").observe(30.0)
    coord.counter("fleet.worker.deaths", worker="w9").bump()
    coord.counter("fleet.lease.requeues").bump(2)
    coord.counter("fleet.tickets.completed").bump(7)
    write_metrics_file(spool, "coordinator", coord.snapshot())
    T.append_trace(
        spool.trace_path("b2.json"),
        T.trace_span_record("claim", 1.0, 1.1, worker="w0",
                            batch="b2.json"),
    )
    return spool


def test_fleet_status_from_spool_alone(tmp_path):
    spool = synthetic_spool(tmp_path)
    st = fleet_status(spool.root)
    q = st["queue"]
    assert [b["batch"] for b in q["pending_batches"]] == ["b1.json"]
    assert q["pending_batches"][0]["tickets"] == 2
    assert q["pending_batches"][0]["age_s"] > 1.0
    assert q["claimed_batches"][0]["worker"] == "w0"
    assert q["dead_batches"] == ["b0.json"]
    assert q["results"] == 1
    workers = {w["worker"]: w for w in st["workers"]}
    assert set(workers) == {"w0", "w1"}
    assert workers["w0"]["lease"] == "b2.json"
    assert workers["w0"]["tickets_published"] == 6
    assert workers["w1"]["alive"] is False  # dead-fleet post-mortem
    assert workers["w0"]["execute_count"] == 6
    assert st["latency"]["e2e"]["count"] == 2
    assert st["counters"]["worker_deaths"] == 1
    assert st["counters"]["lease_requeues"] == 2
    assert st["counters"]["tickets_completed"] == 7


def test_fleet_top_renders_synthetic_and_empty_spool(tmp_path):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "fleet_top",
        os.path.join(os.path.dirname(__file__), "..", "tools",
                     "fleet_top.py"),
    )
    fleet_top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(fleet_top)

    spool = synthetic_spool(tmp_path)
    out = fleet_top.render(fleet_status(spool.root))
    for needle in ("w0", "w1", "b2.json", "DEAD b0.json", "e2e p50=",
                   "worker_deaths=1", "dead"):
        assert needle in out, f"{needle!r} missing from:\n{out}"
    # an EMPTY spool (nothing ever ran) still renders
    empty = fleet_status(str(tmp_path / "empty"))
    out2 = fleet_top.render(empty)
    assert "no worker metric flushes" in out2
    # and the CLI path returns 0 against the dead spool
    assert fleet_top.main(["--spool", spool.root]) == 0


# ------------------------------------------------- real-process tracing


def test_cross_process_span_monotonicity(tmp_path):
    """ACCEPTANCE (ISSUE 9): a real 1-worker fleet's completed ticket
    carries a cross-process breakdown whose edges are monotonic
    (submit <= claim <= execute-end <= publish <= readback-done) and
    whose spans tile >= 95% of its measured end-to-end time."""
    fleet = Fleet(
        str(tmp_path / "spool"), "onemax", config=CFG,
        fleet=FleetConfig(
            n_workers=1, max_batch=2, max_wait_ms=5,
            lease_timeout_s=10.0, heartbeat_s=0.3, poll_s=0.05,
            metrics_flush_s=0.2,
        ),
        registry=M.MetricsRegistry(),
    )
    try:
        fleet.start()
        handles = [
            fleet.submit(FleetTicket(size=128, genome_len=16, n=3, seed=s))
            for s in (1, 2)
        ]
        for h in handles:
            res = h.result(timeout=180)
            lat = h.latency()
            spans = [
                lat[f"{k}_ms"]
                for k in ("intake", "spool_wait", "execute", "publish",
                          "readback")
            ]
            assert all(v is not None and v >= 0.0 for v in spans), lat
            assert sum(spans) >= 0.95 * lat["e2e_ms"], lat
            assert res.latency == lat  # result carries the breakdown too
            trace = h.trace()
            for rec in trace:
                T.validate_event(rec)
            by_span = {r["span"]: r for r in trace}
            # the ordered life: intake -> claim -> execute -> publish
            # -> readback, each edge no earlier than the previous
            order = ["intake", "claim", "execute", "publish", "readback"]
            assert all(s in by_span for s in order), sorted(by_span)
            for a, b in zip(order, order[1:]):
                assert by_span[b]["t1"] >= by_span[a]["t0"], (a, b, trace)
            assert by_span["intake"]["t1"] >= by_span["intake"]["t0"]
            # worker-local TicketTiming rides along (the intra-worker
            # split of the execute span): the breakdown's anchored
            # sub-spans nest inside the cross-process execute span
            assert by_span["execute"]["worker"] == "w0"
            assert "local_run" in by_span
            assert by_span["local_run"]["t0"] >= (
                by_span["execute"]["t0"] - 0.05
            )
            assert by_span["local_run"]["t1"] <= (
                by_span["execute"]["t1"] + 0.05
            )
        # the coordinator's fleet histograms saw every ticket
        snap = fleet.registry.histogram("fleet.ticket.e2e_ms").snapshot()
        assert snap.count == 2
        # and the worker's periodic flush reached the spool
        st = fleet.status()
        assert [w["worker"] for w in st["workers"]] == ["w0"]
        assert st["latency"]["e2e"]["count"] == 2
    finally:
        fleet.close()


def test_tracing_off_suppresses_spans(tmp_path):
    fleet = Fleet(
        str(tmp_path / "spool"), "onemax", config=CFG,
        fleet=FleetConfig(
            n_workers=1, max_batch=1, max_wait_ms=0,
            lease_timeout_s=10.0, heartbeat_s=0.3, poll_s=0.05,
            trace=False,
        ),
        registry=M.MetricsRegistry(),
    )
    try:
        fleet.start()
        h = fleet.submit(FleetTicket(size=128, genome_len=16, n=3, seed=4))
        res = h.result(timeout=180)
        assert res.generations == 3
        assert h.latency()["e2e_ms"] is None
        assert res.latency is None
        # no span log was written for the batch
        assert os.listdir(fleet.spool.path("traces")) == []
        assert fleet.registry.histogram(
            "fleet.ticket.e2e_ms"
        ).snapshot().count == 0
    finally:
        fleet.close()
