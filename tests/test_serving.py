"""Multi-tenant batched run engine (serving/): ISSUE 4 acceptance.

The contracts under test:

- **bit-exactness**: every run of a mega-run — in BOTH run-axis
  layouts — produces the identical final population, scores, and
  telemetry history slice as a standalone same-seed ``PGA.run``,
  including runs with distinct per-run mutation rates sharing one
  program;
- **per-run early termination**: runs with different targets/budgets in
  one batch each stop at exactly the generation their sequential
  counterpart stops at, and finished runs' results are frozen;
- **bucket routing**: mismatched shape signatures never share a
  program — the queue splits them into separate launches, and a direct
  mixed ``run()`` call refuses;
- **compile-once**: a second same-bucket submission triggers 0 new
  builds (asserted via the cache hit/miss counters), and the LRU cache
  evicts at capacity;
- **queue mechanics**: ``max_batch`` launches inline, ``max_wait_ms``
  launches from the background flusher, ``drain()`` completes
  everything, and the batch_admit/batch_launch/compile event stream
  validates against the telemetry schema;
- **cache-key hygiene** (ISSUE 4 satellite): every engine/islands
  compile-cache key is namespaced with a ``<role>/`` prefix, so no
  engine-level key can ever collide with an operator
  ``kernel_cache_key``;
- **failure isolation** (ISSUE 5): a failing run inside a mega-batch
  fails only its own ticket — poisoned requests dead-letter with their
  diagnosis, co-batched tickets complete bit-identically, a transient
  launch failure is requeued once; plus bounded-queue backpressure
  (``max_pending`` + block/raise overflow), deterministic ``close()``
  (flusher joined; post-close submit raises even under concurrent
  submitters), and re-awaitable ticket timeouts.
"""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from libpga_tpu import PGA, PGAConfig, ServingConfig, TelemetryConfig
from libpga_tpu.ops.mutate import make_point_mutate
from libpga_tpu.robustness import faults
from libpga_tpu.serving import (
    COUNTERS,
    BatchedRuns,
    ProgramCache,
    QueueFull,
    RunQueue,
    RunRequest,
)

POP, LEN = 256, 16


def _executor(tel_gens=0, **cfg):
    tel = TelemetryConfig(history_gens=tel_gens) if tel_gens else None
    return BatchedRuns(
        "onemax",
        config=PGAConfig(use_pallas=False, telemetry=tel, **cfg),
        serving=ServingConfig(aot_warmup=True),
    )


def _engine_run(seed, n, target=None, rate=None, tel_gens=0, pop=POP,
                length=LEN):
    tel = TelemetryConfig(history_gens=tel_gens) if tel_gens else None
    pga = PGA(seed=seed, config=PGAConfig(use_pallas=False, telemetry=tel))
    h = pga.create_population(pop, length)
    pga.set_objective("onemax")
    if rate is not None:
        pga.set_mutate(make_point_mutate(rate))
    gens = pga.run(n, target=target)
    return pga, h, gens


# ------------------------------------------------------------ bit-exactness


@pytest.mark.parametrize("layout", ["run_major", "lockstep"])
def test_batched_bit_identical_to_sequential_runs(layout):
    """Same seeds → identical final populations, scores, and history
    slices, for runs with DISTINCT mutation rates sharing one program."""
    ex = _executor(tel_gens=16)
    rates = [0.01, 0.05, 0.02, 0.08]
    reqs = [
        RunRequest(size=POP, genome_len=LEN, n=5, seed=30 + i,
                   mutation_rate=r)
        for i, r in enumerate(rates)
    ]
    results = ex.run(reqs, layout=layout)
    for i, (r, rate) in enumerate(zip(results, rates)):
        pga, h, gens = _engine_run(30 + i, 5, rate=rate, tel_gens=16)
        assert r.generations == gens == 5
        np.testing.assert_array_equal(
            np.asarray(r.genomes), np.asarray(pga.population(h).genomes)
        )
        np.testing.assert_array_equal(
            np.asarray(r.scores), np.asarray(pga.population(h).scores)
        )
        hist = pga.history(h)
        assert len(r.history) == len(hist)
        np.testing.assert_array_equal(r.history.best, hist.best)
        np.testing.assert_array_equal(r.history.stall, hist.stall)


def test_layouts_agree():
    ex = _executor()
    reqs = [
        RunRequest(size=POP, genome_len=LEN, n=4, seed=60 + i)
        for i in range(3)
    ]
    a = ex.run(reqs, layout="run_major")
    b = ex.run(reqs, layout="lockstep")
    for ra, rb in zip(a, b):
        np.testing.assert_array_equal(
            np.asarray(ra.genomes), np.asarray(rb.genomes)
        )
        assert ra.generations == rb.generations


@pytest.mark.parametrize("layout", ["run_major", "lockstep"])
def test_per_run_early_termination_freeze(layout):
    """Distinct per-run targets and budgets: each run stops exactly
    where its sequential counterpart stops, and the generation that
    reached the target is the one returned (not its offspring)."""
    ex = _executor()
    # Targets straddling reachability: request 0 terminates early,
    # request 1 never reaches (runs its full budget), request 2 has a
    # smaller budget than the others.
    specs = [
        (90, 40, float(LEN) * 0.56), (91, 12, float(LEN)), (92, 3, None),
    ]
    reqs = [
        RunRequest(size=POP, genome_len=LEN, n=n, seed=s, target=t)
        for s, n, t in specs
    ]
    results = ex.run(reqs, layout=layout)
    gens_seen = []
    for r, (seed, n, target) in zip(results, specs):
        pga, h, gens = _engine_run(seed, n, target=target)
        assert r.generations == gens
        gens_seen.append(gens)
        np.testing.assert_array_equal(
            np.asarray(r.genomes), np.asarray(pga.population(h).genomes)
        )
        if target is not None and gens < n:
            assert r.best_score >= target
    # The early-stop spec must actually have stopped early, or this
    # test exercises nothing.
    assert gens_seen[0] < 40
    assert gens_seen[1] == 12
    assert gens_seen[2] == 3


def test_explicit_genomes_and_key_match_engine_state_run():
    """A request built from a live engine's population + next key is
    served bit-identically to calling run() on that engine — the C
    ABI's pga_submit contract."""
    pga = PGA(seed=7, config=PGAConfig(use_pallas=False))
    h = pga.create_population(POP, LEN)
    pga.set_objective("onemax")
    ex = _executor()
    req = RunRequest(
        size=POP, genome_len=LEN, n=4,
        genomes=pga.population(h).genomes, key=pga.next_key(),
    )
    # Replay the same state transition on a clone engine.
    pga2 = PGA(seed=7, config=PGAConfig(use_pallas=False))
    h2 = pga2.create_population(POP, LEN)
    pga2.set_objective("onemax")
    (result,) = ex.run([req])
    assert pga2.run(4) == 4
    np.testing.assert_array_equal(
        np.asarray(result.genomes), np.asarray(pga2.population(h2).genomes)
    )


def test_ragged_batch_padding_preserves_results():
    """A non-power-of-two batch pads to the next compiled width; pad
    runs must not perturb real runs."""
    ex = _executor()
    reqs = [
        RunRequest(size=POP, genome_len=LEN, n=4, seed=70 + i)
        for i in range(3)
    ]
    results = ex.run(reqs)
    assert len(results) == 3
    for i, r in enumerate(results):
        pga, h, _ = _engine_run(70 + i, 4)
        np.testing.assert_array_equal(
            np.asarray(r.genomes), np.asarray(pga.population(h).genomes)
        )


# ----------------------------------------------------------- compile cache


def test_second_same_bucket_submission_compiles_nothing():
    """The acceptance gate: one build for the first mega-run of a
    bucket; the second identical submission is a pure cache hit."""
    ex = _executor()
    reqs = [
        RunRequest(size=POP, genome_len=LEN, n=3, seed=80 + i)
        for i in range(2)
    ]
    ex.run(reqs)  # may build or hit depending on suite order
    before = COUNTERS.snapshot()
    ex.run([
        RunRequest(size=POP, genome_len=LEN, n=9, seed=99,
                   mutation_rate=0.07, target=12.3),
        RunRequest(size=POP, genome_len=LEN, n=2, seed=98),
    ])
    after = COUNTERS.snapshot()
    assert after.get("builds", 0) - before.get("builds", 0) == 0
    assert after.get("hits", 0) - before.get("hits", 0) == 1


def test_distinct_shapes_distinct_programs():
    ex = _executor()
    a = RunRequest(size=POP, genome_len=LEN, n=2, seed=1)
    b = RunRequest(size=POP * 2, genome_len=LEN, n=2, seed=1)
    c = RunRequest(size=POP, genome_len=LEN * 2, n=2, seed=1)
    sigs = {ex.signature(a), ex.signature(b), ex.signature(c)}
    assert len(sigs) == 3
    with pytest.raises(ValueError, match="mixed bucket"):
        ex.run([a, b])


def test_program_cache_lru_eviction():
    cache = ProgramCache(capacity=2, counters=None)
    # Private counters so suite-order noise can't leak in.
    cache.counters = type(COUNTERS)()
    cache.get_or_build(("a",), lambda: "A")
    cache.get_or_build(("b",), lambda: "B")
    assert cache.get_or_build(("a",), lambda: "A2") == "A"  # refreshes a
    cache.get_or_build(("c",), lambda: "C")  # evicts b (LRU)
    assert cache.counters.get("evictions") == 1
    assert ("b",) not in cache
    assert ("a",) in cache and ("c",) in cache
    snap = cache.stats()
    assert snap["builds"] == 3
    assert snap["entries"] == 2


# ------------------------------------------------------------------- queue


def test_queue_max_batch_inline_launch():
    ex = _executor()
    q = RunQueue(ex, serving=ServingConfig(max_batch=3, max_wait_ms=0))
    tickets = [
        q.submit(RunRequest(size=POP, genome_len=LEN, n=3, seed=i))
        for i in range(2)
    ]
    assert not any(t.poll() for t in tickets)
    tickets.append(
        q.submit(RunRequest(size=POP, genome_len=LEN, n=3, seed=2))
    )
    assert all(t.poll() for t in tickets)  # the filling submit launched
    assert q.launches == 1
    assert tickets[0].result(timeout=60).generations == 3
    q.close()


def test_queue_result_forces_flush():
    ex = _executor()
    q = RunQueue(ex, serving=ServingConfig(max_batch=32, max_wait_ms=0))
    t = q.submit(RunRequest(size=POP, genome_len=LEN, n=3, seed=5))
    assert not t.poll()
    assert t.result(timeout=60).generations == 3  # flushes its bucket
    q.close()


def test_queue_max_wait_ms_background_flush():
    """A bucket below max_batch launches from the background flusher
    once its oldest request has waited max_wait_ms — no caller action."""
    ex = _executor()
    q = RunQueue(ex, serving=ServingConfig(max_batch=32, max_wait_ms=40.0))
    tickets = [
        q.submit(RunRequest(size=POP, genome_len=LEN, n=3, seed=10 + i))
        for i in range(2)
    ]
    deadline = time.monotonic() + 30.0
    while not all(t.poll() for t in tickets):
        if time.monotonic() > deadline:
            pytest.fail("max_wait_ms flush never fired")
        time.sleep(0.01)
    assert q.launches == 1
    q.close()


def test_queue_routes_mismatched_shapes_to_separate_launches():
    ex = _executor()
    q = RunQueue(ex, serving=ServingConfig(max_batch=2, max_wait_ms=0))
    t1 = q.submit(RunRequest(size=POP, genome_len=LEN, n=2, seed=1))
    t2 = q.submit(RunRequest(size=POP * 2, genome_len=LEN, n=2, seed=2))
    # Neither bucket filled: shapes never share a bucket.
    assert not t1.poll() and not t2.poll()
    assert q.drain() == 2  # one launch per shape bucket
    assert t1.result(timeout=60).generations == 2
    assert t2.result(timeout=60).generations == 2
    assert t1.bucket != t2.bucket
    q.close()


def test_queue_events_validate_and_one_compile_per_bucket(tmp_path):
    """batch_admit / batch_launch / compile flow through the telemetry
    event log, validate against the schema, and a bucket compiles ONCE
    across repeated same-bucket submissions."""
    from libpga_tpu.utils import telemetry

    path = str(tmp_path / "serving-events.jsonl")
    with telemetry.EventLog(path) as log:
        ex = BatchedRuns(
            "onemax", config=PGAConfig(use_pallas=False), events=log
        )
        q = RunQueue(
            ex, serving=ServingConfig(max_batch=2, max_wait_ms=0),
            events=log,
        )
        for round_ in range(2):
            ts = [
                q.submit(
                    RunRequest(size=POP, genome_len=LEN, n=2,
                               seed=round_ * 10 + i)
                )
                for i in range(2)
            ]
            for t in ts:
                t.result(timeout=60)
        q.close()
    records = telemetry.validate_log(path)
    kinds = [r["event"] for r in records]
    assert kinds.count("batch_admit") == 4
    assert kinds.count("batch_launch") == 2
    launches = [r for r in records if r["event"] == "batch_launch"]
    assert all(r["batch_size"] == 2 for r in launches)
    # One bucket, therefore AT MOST one actual program build; a warm
    # program cache (suite order) legally yields zero.
    compiles = [
        r for r in records
        if r["event"] == "compile" and r["what"] == "serving_mega_run"
    ]
    assert len(compiles) <= 1
    admits = {r["bucket"] for r in records if r["event"] == "batch_admit"}
    assert len(admits) == 1


def test_queue_error_propagates_to_tickets():
    ex = _executor()
    q = RunQueue(ex, serving=ServingConfig(max_batch=1, max_wait_ms=0))
    bad = RunRequest(
        size=POP, genome_len=LEN, n=2, seed=1,
        genomes=np.zeros((POP, LEN + 1), np.float32),
    )
    t = q.submit(bad)
    with pytest.raises(ValueError, match="genomes"):
        t.result(timeout=60)
    q.close()


# -------------------------------------------------- failure isolation (I5)


def test_poisoned_request_fails_only_its_ticket():
    """ISSUE 5 tentpole fix of the pinned pre-robustness semantics: one
    raising request in a mixed bucket used to error EVERY co-batched
    ticket; now it dead-letters alone and the co-batched tickets
    complete bit-identically to a fault-free batch."""
    ex = _executor()
    q = RunQueue(ex, serving=ServingConfig(max_batch=3, max_wait_ms=0))
    good = [RunRequest(size=POP, genome_len=LEN, n=3, seed=40 + i)
            for i in range(2)]
    poisoned = RunRequest(
        size=POP, genome_len=LEN, n=3, seed=49,
        genomes=np.zeros((POP, LEN + 1), np.float32),
    )
    t0 = q.submit(good[0])
    t_bad = q.submit(poisoned)
    t1 = q.submit(good[1])  # fills the bucket → inline launch
    with pytest.raises(ValueError, match="genomes"):
        t_bad.result(timeout=60)
    r0, r1 = t0.result(timeout=60), t1.result(timeout=60)
    assert len(q.dead_letters) == 1
    assert q.dead_letters[0].request is poisoned
    assert isinstance(q.dead_letters[0].error, ValueError)
    ref = _executor().run(good)
    np.testing.assert_array_equal(
        np.asarray(r0.genomes), np.asarray(ref[0].genomes)
    )
    np.testing.assert_array_equal(
        np.asarray(r1.genomes), np.asarray(ref[1].genomes)
    )
    q.close()


def test_transient_launch_fault_requeues_once_and_recovers(tmp_path):
    from libpga_tpu.utils import telemetry

    path = str(tmp_path / "iso.jsonl")
    with telemetry.EventLog(path) as log:
        ex = BatchedRuns(
            "onemax", config=PGAConfig(use_pallas=False), events=log
        )
        q = RunQueue(
            ex, serving=ServingConfig(max_batch=2, max_wait_ms=0),
            events=log,
        )
        reqs = [RunRequest(size=POP, genome_len=LEN, n=3, seed=50 + i)
                for i in range(2)]
        with faults.active(faults.FaultPlan("serving.launch", at_call_n=1)):
            tickets = [q.submit(r) for r in reqs]
            results = [t.result(timeout=60) for t in tickets]
        q.close()
    assert q.requeues == 1 and not q.dead_letters
    ref = _executor().run(reqs)
    for r, rr in zip(results, ref):
        np.testing.assert_array_equal(
            np.asarray(r.genomes), np.asarray(rr.genomes)
        )
    records = telemetry.validate_log(path)
    retries = [r for r in records if r["event"] == "retry"]
    assert len(retries) == 1 and retries[0]["attempt"] == 1


def test_dead_letter_event_validates(tmp_path):
    from libpga_tpu.utils import telemetry

    path = str(tmp_path / "dl.jsonl")
    with telemetry.EventLog(path) as log:
        ex = BatchedRuns(
            "onemax", config=PGAConfig(use_pallas=False), events=log
        )
        q = RunQueue(
            ex, serving=ServingConfig(max_batch=1, max_wait_ms=0),
            events=log,
        )
        t = q.submit(RunRequest(
            size=POP, genome_len=LEN, n=2, seed=1,
            genomes=np.zeros((POP, LEN + 1), np.float32),
        ))
        with pytest.raises(ValueError):
            t.result(timeout=60)
        q.close()
    records = telemetry.validate_log(path)
    dead = [r for r in records if r["event"] == "dead_letter"]
    assert len(dead) == 1
    assert "genomes" in dead[0]["error"]


def test_executor_validate_diagnoses():
    ex = _executor()
    ok = RunRequest(size=POP, genome_len=LEN, n=2, seed=0)
    assert ex.validate(ok) is None
    bad_shape = RunRequest(
        size=POP, genome_len=LEN, n=2, seed=0,
        genomes=np.zeros((POP + 1, LEN), np.float32),
    )
    assert isinstance(ex.validate(bad_shape), ValueError)
    bad_rate = RunRequest(
        size=POP, genome_len=LEN, n=2, seed=0, mutation_rate=1.5
    )
    assert isinstance(ex.validate(bad_rate), ValueError)


# --------------------------------------------------- backpressure (I5)


def test_backpressure_raise_policy():
    ex = _executor()
    q = RunQueue(ex, serving=ServingConfig(
        max_batch=8, max_wait_ms=0, max_pending=2, overflow="raise",
    ))
    q.submit(RunRequest(size=POP, genome_len=LEN, n=1, seed=0))
    q.submit(RunRequest(size=POP, genome_len=LEN, n=1, seed=1))
    assert q.pending == 2
    with pytest.raises(QueueFull):
        q.submit(RunRequest(size=POP, genome_len=LEN, n=1, seed=2))
    q.drain()
    assert q.pending == 0
    # completions free slots: the next submit is admitted again
    t = q.submit(RunRequest(size=POP, genome_len=LEN, n=1, seed=3))
    q.drain()
    assert t.result(timeout=60).generations == 1
    q.close()


def test_backpressure_block_policy_unblocks_on_completion():
    ex = _executor()
    q = RunQueue(ex, serving=ServingConfig(
        max_batch=8, max_wait_ms=0, max_pending=1, overflow="block",
    ))
    q.submit(RunRequest(size=POP, genome_len=LEN, n=1, seed=0))
    admitted = threading.Event()

    def blocked_submit():
        q.submit(RunRequest(size=POP, genome_len=LEN, n=1, seed=1))
        admitted.set()

    worker = threading.Thread(target=blocked_submit, daemon=True)
    worker.start()
    time.sleep(0.1)
    assert not admitted.is_set()  # blocked at the bound
    q.drain()  # completes the first ticket → frees the slot
    assert admitted.wait(10)
    q.drain()
    q.close()
    worker.join(5)


def test_serving_config_backpressure_validation():
    with pytest.raises(ValueError, match="max_pending"):
        ServingConfig(max_pending=0)
    with pytest.raises(ValueError, match="overflow"):
        ServingConfig(overflow="drop")


# ------------------------------------------------ ticket + close semantics


def test_ticket_timeout_leaves_ticket_reawaitable():
    """Satellite pin: result(timeout=) raising TimeoutError must leave
    the ticket intact — a later result() still completes it."""
    ex = _executor()
    q = RunQueue(ex, serving=ServingConfig(max_batch=32, max_wait_ms=0))
    t = q.submit(RunRequest(size=POP, genome_len=LEN, n=2, seed=5))
    # Detach the bucket items as a launch-in-flight elsewhere would, so
    # result()'s force-flush finds nothing and the wait genuinely times
    # out.
    with q._lock:
        sig = q._bucket_names[t.bucket]
        launch = q._take(sig)
    with pytest.raises(TimeoutError):
        t.result(timeout=0.05)
    assert not t.poll()
    q._launch(sig, *launch)  # the in-flight launch lands
    assert t.result(timeout=60).generations == 2  # re-awaitable
    q.close()


def test_close_joins_flusher_and_post_close_submit_raises():
    ex = _executor()
    q = RunQueue(ex, serving=ServingConfig(max_batch=32, max_wait_ms=10.0))
    q.submit(RunRequest(size=POP, genome_len=LEN, n=1, seed=0))
    flusher = q._flusher
    assert flusher is not None and flusher.is_alive()
    q.close()
    assert not flusher.is_alive()  # joined, not just flagged
    with pytest.raises(RuntimeError, match="closed"):
        q.submit(RunRequest(size=POP, genome_len=LEN, n=1, seed=1))


def test_close_under_concurrent_submits_is_deterministic():
    """Satellite: close() must leave no racing flusher iteration and
    every admitted ticket either completes or the submit raised the
    closed error — nothing hangs, nothing launches after close."""
    ex = _executor()
    q = RunQueue(ex, serving=ServingConfig(max_batch=4, max_wait_ms=5.0))
    tickets, closed_errors = [], []
    stop = threading.Event()

    def submitter(base):
        i = 0
        while not stop.is_set():
            try:
                tickets.append(q.submit(RunRequest(
                    size=POP, genome_len=LEN, n=1, seed=base + i,
                )))
            except RuntimeError:
                closed_errors.append(1)
                return
            i += 1

    workers = [
        threading.Thread(target=submitter, args=(1000 * w,), daemon=True)
        for w in range(3)
    ]
    for w in workers:
        w.start()
    time.sleep(0.15)
    q.close()
    stop.set()
    for w in workers:
        w.join(10)
        assert not w.is_alive()
    launches_at_close = q.launches
    # every admitted ticket is completed by close()'s final flush
    for t in list(tickets):
        assert t.result(timeout=60).generations == 1
    # and nothing launched after close() returned
    assert q.launches == launches_at_close
    with pytest.raises(RuntimeError, match="closed"):
        q.submit(RunRequest(size=POP, genome_len=LEN, n=1, seed=9))


def test_close_concurrent_closers_idempotent():
    """Satellite (ISSUE 8): a second close() racing the first is a
    deterministic no-op — exactly one teardown happens, every closer
    returns with the queue fully closed, and nothing launches after
    any of them returned."""
    ex = _executor()
    q = RunQueue(ex, serving=ServingConfig(max_batch=8, max_wait_ms=5.0))
    tickets = [
        q.submit(RunRequest(size=POP, genome_len=LEN, n=1, seed=s))
        for s in range(3)
    ]
    barrier = threading.Barrier(4)
    errors = []

    def closer():
        try:
            barrier.wait(10)
            q.close()
        except BaseException as e:  # pragma: no cover - diagnostic
            errors.append(e)

    closers = [threading.Thread(target=closer, daemon=True)
               for _ in range(4)]
    for t in closers:
        t.start()
    for t in closers:
        t.join(30)
        assert not t.is_alive(), "a concurrent close() hung"
    assert errors == []
    launches_at_close = q.launches
    for t in tickets:  # the single teardown's flush completed them all
        assert t.result(timeout=60).generations == 1
    assert q.launches == launches_at_close
    assert q._flusher is None
    with pytest.raises(RuntimeError, match="closed"):
        q.submit(RunRequest(size=POP, genome_len=LEN, n=1, seed=9))
    # a LATER close() is the same deterministic no-op
    q.close()
    assert q.launches == launches_at_close


# ---------------------------------------------------------------- islands


def test_batched_island_runs_match_stacked_runner():
    """N island runs through the batched loop are bit-identical to N
    separate run_islands_stacked calls with the same keys (the island
    face of the mega-run; reuses build_local_runner's exact loop)."""
    from libpga_tpu import objectives
    from libpga_tpu.ops.crossover import uniform_crossover
    from libpga_tpu.ops.step import make_breed
    from libpga_tpu.parallel.islands import (
        make_batched_island_loop,
        run_islands_stacked,
    )

    obj = objectives.get("onemax")
    breed = make_breed(uniform_crossover, make_point_mutate(0.01))
    N, I, S, L, m, epochs = 3, 2, 64, 16, 2, 3
    mega = jax.jit(
        make_batched_island_loop(breed, obj, m=m, count=3, topology="ring")
    )
    runs = []
    for r in range(N):
        key = jax.random.key(50 + r)
        stacked = jax.random.uniform(jax.random.fold_in(key, 9), (I, S, L))
        runs.append((stacked, key))
    refs = [
        run_islands_stacked(
            breed, obj, g, k, n=epochs * m, m=m, pct=3 / S, topology="ring"
        )
        for g, k in runs
    ]
    island_keys, mig_keys = [], []
    for _, k in runs:
        ks = jax.random.split(k, I + 1)
        mig_keys.append(ks[0])
        island_keys.append(ks[1:])
    g_b, s_b, e_b = mega(
        jnp.stack([g for g, _ in runs]),
        jnp.stack(island_keys),
        jnp.stack(mig_keys),
        jnp.full((N,), epochs, jnp.int32),
        jnp.full((N,), jnp.inf, jnp.float32),
    )
    for r in range(N):
        np.testing.assert_array_equal(
            np.asarray(g_b[r]), np.asarray(refs[r][0])
        )
        np.testing.assert_array_equal(
            np.asarray(s_b[r]), np.asarray(refs[r][1])
        )
        assert int(e_b[r]) * m == refs[r][2]


# ------------------------------------------------------------- validation


def test_request_and_config_validation():
    with pytest.raises(ValueError, match="seed or an explicit key"):
        RunRequest(size=8, genome_len=8, n=1)
    with pytest.raises(ValueError, match="n must be"):
        RunRequest(size=8, genome_len=8, n=-1, seed=0)
    with pytest.raises(ValueError, match="max_batch"):
        ServingConfig(max_batch=0)
    with pytest.raises(ValueError, match="layout"):
        ServingConfig(layout="sideways")
    with pytest.raises(ValueError, match="cache_capacity"):
        ServingConfig(cache_capacity=0)
    with pytest.raises(ValueError, match="mutate kind"):
        from libpga_tpu.ops.step import make_param_breed
        from libpga_tpu.ops.crossover import uniform_crossover

        make_param_breed(uniform_crossover, "bitflip")


# --------------------------------------------------------- cache-key hygiene


def test_compile_cache_keys_are_role_namespaced():
    """Satellite: every engine/islands compile-cache key is a tuple
    whose first element is a '<ns>/<role>' string — structurally
    disjoint from operator kernel_cache_keys (whose role tags carry no
    '/'), so the historical collision class (engine key == operator
    key) is impossible by construction."""
    from libpga_tpu.ops.breed_expr import (
        crossover_from_expression,
        mutate_from_expression,
    )
    from libpga_tpu.ops.crossover import one_point_crossover

    pga = PGA(seed=0, config=PGAConfig(use_pallas=False))
    pga.create_population(64, 8)
    pga.create_population(64, 8)
    pga.set_objective("onemax")
    pga.run(2)
    pga.evaluate_all()
    pga.crossover_all()
    pga.mutate_all()
    pga.run_islands(2, 1, 0.1)
    keys = list(pga._compiled)
    pga._crossover_expr_equivalent("one_point")
    assert pga._crossover_kind() is not None  # populates nothing extra
    pga.set_crossover(one_point_crossover)  # clears the cache...
    pga.run(1)
    keys += list(pga._compiled)  # ...so union both generations of keys
    assert keys, "no compiled entries exercised"
    namespaces = set()
    for key in keys:
        assert isinstance(key, tuple), f"bare key {key!r}"
        assert isinstance(key[0], str) and "/" in key[0], (
            f"un-namespaced cache key {key!r}"
        )
        namespaces.add(key[0].split("/", 1)[0])
    assert namespaces <= {"engine", "islands", "serving"}
    assert "engine" in namespaces and "islands" in namespaces

    # Operator kernel_cache_keys can never equal an engine-level key.
    cross_op = crossover_from_expression("where(r < 0.5, p1, p2)")
    mut_op = mutate_from_expression("where(r < rate, r2, g)")
    for op_key in (cross_op.kernel_cache_key, mut_op.kernel_cache_key):
        assert op_key not in pga._compiled
        assert "/" not in op_key[0]


def test_serving_signature_separates_config_changes():
    """Config fields that shape the program split buckets; runtime
    inputs don't."""
    base = _executor()
    elitist = _executor(elitism=2)
    req = RunRequest(size=POP, genome_len=LEN, n=2, seed=0)
    assert base.signature(req) != elitist.signature(req)
    r2 = RunRequest(
        size=POP, genome_len=LEN, n=99, seed=123, target=5.0,
        mutation_rate=0.3,
    )
    assert base.signature(req) == base.signature(r2)


# --------------------------------------------- ticket lifecycle (ISSUE 6)
#
# Per-ticket latency tracing: every ticket carries monotonic stamps for
# submit -> bucket-admit -> launch -> run-complete -> readback, the
# latency() breakdown derives from them, and the queue folds completed
# tickets into registry histograms + ticket_done events. The dead-letter
# and solo-requeue paths keep their stamps up to the failure point.


def _traced_queue(max_batch=4, slo=None, **serving_kw):
    from libpga_tpu.utils.metrics import MetricsRegistry

    reg = MetricsRegistry()
    q = RunQueue(
        _executor(),
        serving=ServingConfig(
            max_batch=max_batch, max_wait_ms=0, **serving_kw
        ),
        registry=reg,
        slo=slo,
    )
    return q, reg


def test_ticket_lifecycle_monotonic_and_complete():
    q, reg = _traced_queue(max_batch=3)
    tickets = [
        q.submit(RunRequest(size=POP, genome_len=LEN, n=3, seed=i))
        for i in range(3)
    ]
    for t in tickets:
        t.result(timeout=300)
        tm = t.timing
        assert (
            tm.submitted <= tm.admitted <= tm.launched
            <= tm.completed <= tm.readback
        ), tm
        lat = t.latency()
        assert set(lat) == {
            "queue_wait_ms", "execute_ms", "readback_ms", "e2e_ms"
        }
        assert all(v is not None and v >= 0.0 for v in lat.values())
        # e2e covers the component spans (equality up to fp rounding)
        assert lat["e2e_ms"] >= max(
            lat["queue_wait_ms"], lat["execute_ms"]
        ) - 1e-6
    # histograms saw every ticket; occupancy recorded the full batch
    assert reg.histogram("serving.ticket.e2e_ms").count == 3
    assert reg.histogram("serving.batch.occupancy").count == 1
    assert reg.counter("serving.tickets_done").value == 3
    q.close()


def test_drain_preserves_ticket_timing():
    """drain() completes the runs without discarding the breakdown:
    launch/complete are stamped at drain time, readback at result()."""
    q, _ = _traced_queue(max_batch=64)  # never fills inline
    t = q.submit(RunRequest(size=POP, genome_len=LEN, n=2, seed=0))
    assert t.timing.submitted is not None and t.timing.launched is None
    q.drain()
    assert t.timing.launched is not None
    assert t.timing.completed is not None
    assert t.timing.readback is None  # not read back yet
    t.result(timeout=300)
    tm = t.timing
    assert tm.submitted <= tm.admitted <= tm.launched \
        <= tm.completed <= tm.readback
    q.close()


def test_dead_letter_ticket_keeps_stamps_to_failure_point():
    """Satellite: a dead-lettered ticket still carries timestamps up to
    the failure — submit/admit/launch/complete set, readback never."""
    q, reg = _traced_queue(max_batch=3)
    good = [
        q.submit(RunRequest(size=POP, genome_len=LEN, n=2, seed=i))
        for i in range(2)
    ]
    poisoned = q.submit(RunRequest(
        size=POP, genome_len=LEN, n=2, seed=9,
        genomes=np.zeros((4, 4), np.float32),
    ))
    q.drain()
    with pytest.raises(ValueError):
        poisoned.result(timeout=300)
    tm = poisoned.timing
    assert tm.submitted <= tm.admitted <= tm.launched <= tm.completed
    assert tm.readback is None
    assert poisoned.latency()["readback_ms"] is None
    assert poisoned.latency()["e2e_ms"] is not None  # up to completion
    # the survivors went through the solo-requeue path: restamped
    # launches still ordered, full breakdown present
    for t in good:
        t.result(timeout=300)
        tm = t.timing
        assert tm.submitted <= tm.admitted <= tm.launched \
            <= tm.completed <= tm.readback
    assert q.requeues == 1 and len(q.dead_letters) == 1
    assert reg.counter("serving.dead_letters").value == 1
    assert reg.gauge("serving.dead_letters.pending").value == 1
    q.close()


def test_dead_letter_dumps_flight_recorder(tmp_path, monkeypatch):
    from libpga_tpu.utils import telemetry as tl

    monkeypatch.setattr(
        tl, "FLIGHT", tl.FlightRecorder(dump_dir=str(tmp_path))
    )
    q, _ = _traced_queue(max_batch=1)
    t = q.submit(RunRequest(
        size=POP, genome_len=LEN, n=2, seed=0,
        genomes=np.zeros((2, 2), np.float32),
    ))
    with pytest.raises(ValueError):
        t.result(timeout=300)
    assert tl.FLIGHT.dumps, "dead letter did not dump the recorder"
    recs = tl.validate_log(tl.FLIGHT.dumps[-1])
    kinds = [r["event"] for r in recs]
    assert "dead_letter" in kinds
    assert "metrics_snapshot" in kinds and kinds[-1] == "flight_dump"
    q.close()


def test_ticket_done_and_batch_launch_events_validate(tmp_path):
    from libpga_tpu.utils import telemetry as tl

    path = str(tmp_path / "events.jsonl")
    log = tl.EventLog(path)
    from libpga_tpu.utils.metrics import MetricsRegistry

    q = RunQueue(
        _executor(), serving=ServingConfig(max_batch=2, max_wait_ms=0),
        events=log, registry=MetricsRegistry(),
    )
    tickets = [
        q.submit(RunRequest(size=POP, genome_len=LEN, n=2, seed=i))
        for i in range(2)
    ]
    for t in tickets:
        t.result(timeout=300)
    q.close()
    log.close()
    records = tl.validate_log(path)
    done = [r for r in records if r["event"] == "ticket_done"]
    assert len(done) == 2
    for r in done:
        assert r["queue_wait_ms"] >= 0 and r["e2e_ms"] >= r["execute_ms"]
    [launch] = [r for r in records if r["event"] == "batch_launch"]
    assert launch["fill_ratio"] == 1.0


def test_slo_per_ticket_and_aggregate_violations(tmp_path):
    from libpga_tpu import SLOConfig
    from libpga_tpu.utils import telemetry as tl

    path = str(tmp_path / "events.jsonl")
    log = tl.EventLog(path)
    slo = SLOConfig(
        p99_latency_ms=1e-4, max_queue_wait_ms=0.0, min_samples=1
    )
    from libpga_tpu.utils.metrics import MetricsRegistry

    reg = MetricsRegistry()
    q = RunQueue(
        _executor(), serving=ServingConfig(max_batch=2, max_wait_ms=0),
        events=log, registry=reg, slo=slo,
    )
    tickets = [
        q.submit(RunRequest(size=POP, genome_len=LEN, n=2, seed=i))
        for i in range(2)
    ]
    for t in tickets:
        t.result(timeout=300)
    violations = q.check_slo()
    assert violations and violations[0]["what"] == "p99_latency"
    # an un-SLO'd queue reports nothing
    q2 = RunQueue(
        _executor(), serving=ServingConfig(max_batch=1, max_wait_ms=0),
        registry=MetricsRegistry(),
    )
    assert q2.check_slo() == []
    q.close()
    q2.close()
    log.close()
    records = tl.validate_log(path)
    slo_events = [r for r in records if r["event"] == "slo_violation"]
    whats = {r["what"] for r in slo_events}
    assert "queue_wait" in whats and "p99_latency" in whats
    assert reg.counter("serving.slo_violations").value == len(slo_events)


def test_queue_depth_and_bucket_gauges_settle_to_zero():
    q, reg = _traced_queue(max_batch=2)
    tickets = [
        q.submit(RunRequest(size=POP, genome_len=LEN, n=2, seed=i))
        for i in range(2)
    ]
    for t in tickets:
        t.result(timeout=300)
    assert reg.gauge("serving.queue.depth").value == 0
    [bucket] = [
        rec for rec in reg.snapshot()["gauges"]
        if rec["name"] == "serving.bucket.pending"
    ]
    assert bucket["value"] == 0
    q.close()
