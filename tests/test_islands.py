"""Island-model tests on the simulated 8-device CPU mesh — the distributed
coverage the reference entirely lacks (its island API is all stubs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libpga_tpu import PGA, PGAConfig
from libpga_tpu.ops.crossover import uniform_crossover
from libpga_tpu.ops.mutate import make_point_mutate
from libpga_tpu.ops.step import make_breed
from libpga_tpu.parallel.islands import run_islands_stacked
from libpga_tpu.parallel.mesh import default_mesh


OBJ = lambda g: jnp.sum(g)


def _breed():
    return make_breed(uniform_crossover, make_point_mutate(0.01))


def test_local_islands_converge(key):
    stacked = jax.random.uniform(key, (4, 256, 16))
    g, s, gens = run_islands_stacked(
        _breed(), OBJ, stacked, key, n=30, m=5, pct=0.1
    )
    assert g.shape == stacked.shape
    assert s.shape == (4, 256)
    assert gens == 30
    assert float(jnp.max(s)) > 0.8 * 16


def test_local_islands_remainder_generations(key):
    stacked = jax.random.uniform(key, (2, 64, 8))
    _, _, gens = run_islands_stacked(
        _breed(), OBJ, stacked, key, n=13, m=5, pct=0.1
    )
    assert gens == 13  # 2 epochs of 5 + remainder 3


def test_local_islands_early_termination(key):
    stacked = jax.random.uniform(key, (4, 512, 8))
    _, s, gens = run_islands_stacked(
        _breed(), OBJ, stacked, key, n=10_000, m=5, pct=0.1, target=7.0
    )
    assert gens < 10_000
    assert float(jnp.max(s)) >= 7.0


def test_random_topology(key):
    stacked = jax.random.uniform(key, (4, 128, 8))
    g, s, gens = run_islands_stacked(
        _breed(), OBJ, stacked, key, n=10, m=5, pct=0.1, topology="random"
    )
    assert gens == 10
    assert bool(jnp.all(jnp.isfinite(s)))


def test_migration_spreads_best(key):
    """Plant a super-individual in island 0; after one migration epoch the
    ring neighbor must contain it (or better)."""
    stacked = jax.random.uniform(key, (4, 64, 8)) * 0.1
    stacked = stacked.at[0, 0].set(jnp.ones(8) * 0.999)
    # Disable evolution effects as much as possible: 1 generation per epoch.
    g, s, _ = run_islands_stacked(
        _breed(), OBJ, stacked, key, n=2, m=1, pct=0.05
    )
    # elite was in island 0 → island 1 should have received high genomes
    assert float(jnp.max(s[1])) > 4.0


@pytest.mark.parametrize("topology", ["ring", "random"])
def test_sharded_islands_match_shape(key, topology):
    mesh = default_mesh()
    n_dev = mesh.devices.size
    assert n_dev == 8  # conftest forces 8 CPU devices
    stacked = jax.random.uniform(key, (8, 128, 16))
    g, s, gens = run_islands_stacked(
        _breed(), OBJ, stacked, key, n=20, m=5, pct=0.1,
        topology=topology, mesh=mesh,
    )
    assert g.shape == (8, 128, 16)
    assert s.shape == (8, 128)
    assert gens == 20
    assert float(jnp.max(s)) > 0.75 * 16


def test_sharded_multiple_islands_per_device(key):
    mesh = default_mesh()
    stacked = jax.random.uniform(key, (16, 64, 8))  # 2 islands per device
    g, s, gens = run_islands_stacked(
        _breed(), OBJ, stacked, key, n=10, m=5, pct=0.1, mesh=mesh
    )
    assert g.shape == (16, 64, 8)
    assert gens == 10


def test_sharded_islands_uneven_rejected(key):
    mesh = default_mesh()
    stacked = jax.random.uniform(key, (6, 32, 8))  # 6 % 8 != 0
    with pytest.raises(ValueError):
        run_islands_stacked(
            _breed(), OBJ, stacked, key, n=5, m=5, pct=0.1, mesh=mesh
        )


def test_sharded_ring_migration_propagates(key):
    """Super-individual on device-0's island must reach device 1 via the
    ppermute ring."""
    mesh = default_mesh()
    stacked = jax.random.uniform(key, (8, 64, 8)) * 0.1
    stacked = stacked.at[0, 0].set(jnp.ones(8) * 0.999)
    g, s, _ = run_islands_stacked(
        _breed(), OBJ, stacked, key, n=2, m=1, pct=0.05, mesh=mesh
    )
    assert float(jnp.max(s[1])) > 4.0


def test_engine_run_islands_end_to_end():
    pga = PGA(seed=0)
    for _ in range(4):
        pga.create_population(128, 8)
    pga.set_objective("onemax")
    gens = pga.run_islands(20, 5, 0.1)
    assert gens == 20
    best = pga.get_best_all()
    assert best.sum() > 0.75 * 8


def test_engine_run_islands_sharded():
    pga = PGA(seed=0)
    for _ in range(8):
        pga.create_population(64, 8)
    pga.set_objective("onemax")
    mesh = default_mesh()
    gens = pga.run_islands(10, 5, 0.1, mesh=mesh)
    assert gens == 10
    assert pga.get_best_all().shape == (8,)


def test_engine_run_islands_heterogeneous_fallback():
    pga = PGA(seed=0)
    pga.create_population(64, 8)
    pga.create_population(128, 8)  # different size → hetero path
    pga.set_objective("onemax")
    gens = pga.run_islands(10, 5, 0.1)
    assert gens == 10


def test_multigen_stacked_epoch_runs_islands():
    """The multi-generation island epoch (one vmapped kernel launch per
    <=8-generation chunk by default, in-kernel ranking) drives run_islands_stacked
    end-to-end in interpret mode: generations counted exactly, scores
    consistent with genomes, migration applied."""
    from jax.experimental.pallas import tpu as pltpu

    from libpga_tpu.objectives import get as get_obj
    from libpga_tpu.ops.pallas_step import make_pallas_multigen

    obj = get_obj("onemax")
    I, S, L = 4, 256, 16
    with pltpu.force_tpu_interpret_mode():
        bm = make_pallas_multigen(
            S, L, deme_size=128,
            fused_obj=obj.kernel_rowwise,
            fused_consts=tuple(getattr(obj, "kernel_rowwise_consts", ())),
        )
        assert bm is not None and getattr(bm, "multigen", False)
        stacked = jax.random.uniform(
            jax.random.key(0), (I, S, L), dtype=jnp.float32
        )
        g, s, gens = run_islands_stacked(
            bm, obj, stacked, jax.random.key(1), n=7, m=3, pct=0.1
        )
    assert gens == 7
    np.testing.assert_allclose(
        np.asarray(s), np.asarray(jnp.sum(g, axis=2)), rtol=1e-4
    )
    mean0 = float(jnp.mean(jnp.sum(stacked, axis=2)))
    assert float(jnp.mean(s)) > mean0
