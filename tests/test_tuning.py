"""Self-tuning kernels (ISSUE 10): config space, tuning DB, resolution
precedence, autotuner determinism, serving cache-key inclusion."""

import json
import os
import threading
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libpga_tpu import PGA, PGAConfig
from libpga_tpu.tuning import db as tdb
from libpga_tpu.tuning import space
from libpga_tpu.tuning import set_tuning_db


@pytest.fixture(autouse=True)
def _clean_tuning_state():
    yield
    set_tuning_db(None)


def _entry(pop=256, length=16, knobs=None, gps=5.0, created=1.0,
           objective="onemax"):
    return tdb.TuningEntry(
        key=tdb.current_key(pop, length, jnp.float32, objective),
        knobs=knobs or {"pallas_deme_size": 256, "pallas_layout": None,
                        "pallas_subblock": None},
        gens_per_sec=gps, created=created,
    )


def _db_file(tmp_path, name, *entries):
    d = tdb.TuningDB()
    for e in entries:
        d.add(e)
    path = str(tmp_path / name)
    d.save(path)
    return path


# ------------------------------------------------------------ config space


class TestSpace:
    def test_zero_genome_is_default_config(self):
        cfg = space.config_from_genes([0.0, 0.0, 0.0])
        assert cfg == space.KernelConfig()
        assert all(
            space.DOMAINS[k][0] is None or k == "dimension_semantics"
            for k in space.DOMAINS
        )

    def test_codec_roundtrip_every_index(self):
        import itertools

        knobs = space.TUNER_KNOBS
        sizes = [len(space.DOMAINS[k]) for k in knobs]
        for idx in itertools.product(*[range(s) for s in sizes]):
            cfg = space.config_from_indices(idx, knobs)
            assert space.indices_from_config(cfg, knobs) == tuple(idx)

    def test_gene_decode_clips_out_of_range(self):
        cfg = space.config_from_genes([5.0, -1.0, 0.999])
        assert cfg.deme_size == space.DOMAINS["deme_size"][-1]
        assert cfg.layout is None
        assert cfg.subblock == space.DOMAINS["subblock"][-1]

    def test_invalid_deme_rejected_before_compile(self):
        ctx = space.SpaceContext(1024, 32)
        bad = space.KernelConfig(deme_size=300)
        reason = space.why_inadmissible(ctx, bad)
        assert reason and "power of two" in reason

    def test_non_dividing_deme_rejected_strict(self):
        ctx = space.SpaceContext(1000, 32)
        reason = space.why_inadmissible(
            ctx, space.KernelConfig(deme_size=512)
        )
        assert reason and "divide" in reason

    def test_subblock_requires_pingpong(self):
        ctx = space.SpaceContext(1 << 16, 64)
        reason = space.why_inadmissible(
            ctx, space.KernelConfig(layout="riffle", subblock=2)
        )
        assert reason and "ping-pong" in reason

    def test_pingpong_gate_reason_names_the_gate(self):
        # A shape where the explicit ping-pong mixing gate fails: tiny
        # pop at max deme size leaves too few chunks per group.
        ctx = space.SpaceContext(256, 16)
        reason = space.why_inadmissible(
            ctx, space.KernelConfig(deme_size=256, layout="pingpong",
                                    subblock=4)
        )
        assert reason is not None

    def test_grid_matches_factory_resolution(self):
        """Every admissible (K, D) the grid yields builds EXACTLY as
        asked — the sweep tools' old build-and-check loop, now a
        guarantee of the space."""
        from libpga_tpu.ops.pallas_step import make_pallas_breed
        from libpga_tpu.objectives import onemax

        ctx = space.SpaceContext(1 << 14, 32)
        cfgs = space.grid(
            ctx, ("deme_size", "demes_per_step"),
            deme_size=(128, 256, 512), demes_per_step=(1, 2, 4),
            layout=("riffle",),
        )
        assert cfgs, "grid admitted nothing at a healthy shape"
        for cfg in cfgs:
            b = make_pallas_breed(
                1 << 14, 32, deme_size=cfg.deme_size,
                fused_obj=onemax.kernel_rowwise,
                _demes_per_step=cfg.demes_per_step, _layout="riffle",
            )
            assert b is not None
            assert (b.K, b.D) == (cfg.deme_size, cfg.demes_per_step)

    def test_space_size_counts_admissible(self):
        ctx = space.SpaceContext(2048, 64)
        assert space.space_size(ctx) == len(
            space.grid(ctx, space.TUNER_KNOBS)
        )


# -------------------------------------------------------------- tuning DB


class TestTuningDB:
    def test_roundtrip(self, tmp_path):
        e = _entry()
        path = _db_file(tmp_path, "t.json", e)
        loaded = tdb.TuningDB.load(path)
        assert loaded.lookup(e.key).knobs == e.knobs

    def test_schema_version_refusal(self, tmp_path):
        path = str(tmp_path / "future.json")
        with open(path, "w") as fh:
            json.dump({"schema_version": 99, "entries": {}}, fh)
        with pytest.raises(tdb.TuningSchemaError):
            tdb.TuningDB.load(path)
        # merge REFUSES loudly too — never skip a parseable future DB.
        with pytest.raises(tdb.TuningSchemaError):
            tdb.merge_files([path])

    def test_torn_file_load_raises_naming_path(self, tmp_path):
        path = str(tmp_path / "torn.json")
        with open(path, "w") as fh:
            fh.write('{"schema_version": 1, "entries": {"x"')
        with pytest.raises(tdb.TuningDBError) as exc:
            tdb.TuningDB.load(path)
        assert "torn" in str(exc.value)

    def test_merge_skips_and_reports_torn(self, tmp_path):
        good = _db_file(tmp_path, "good.json", _entry())
        torn = str(tmp_path / "torn.json")
        with open(torn, "w") as fh:
            fh.write('{"schema_version": 1, "entr')
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            merged, skipped = tdb.merge_files([good, torn])
        assert len(merged) == 1
        assert skipped == [torn]
        assert any("skipped 1 torn" in str(x.message) for x in w)

    def test_merge_missing_file_is_silent(self, tmp_path):
        good = _db_file(tmp_path, "good.json", _entry())
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            merged, skipped = tdb.merge_files(
                [good, str(tmp_path / "absent.json")]
            )
        assert len(merged) == 1 and skipped == []
        assert not w

    def test_merge_associative_and_commutative(self):
        # Same key, three conflicting entries; plus disjoint keys.
        a = tdb.TuningDB()
        a.add(_entry(gps=5.0, created=1.0))
        b = tdb.TuningDB()
        b.add(_entry(gps=9.0, created=2.0))
        b.add(_entry(pop=512, gps=1.0))
        c = tdb.TuningDB()
        c.add(_entry(gps=9.0, created=3.0))  # tie on gps → created
        left = a.merge(b).merge(c)
        right = a.merge(b.merge(c))
        swapped = c.merge(a).merge(b)
        for m in (right, swapped):
            assert {
                k: e.as_dict() for k, e in left.entries.items()
            } == {k: e.as_dict() for k, e in m.entries.items()}
        winner = left.lookup(_entry().key)
        assert winner.gens_per_sec == 9.0 and winner.created == 3.0

    def test_atomic_write_under_concurrent_reader(self, tmp_path):
        path = str(tmp_path / "live.json")
        tdb.TuningDB().save(path)
        stop = threading.Event()
        errors = []

        def writer():
            i = 0
            while not stop.is_set():
                d = tdb.TuningDB()
                d.add(_entry(gps=float(i), created=float(i)))
                d.save(path)
                i += 1

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        try:
            for _ in range(200):
                try:
                    tdb.TuningDB.load(path)  # must never see a prefix
                except tdb.TuningDBError as exc:
                    errors.append(exc)
                    break
        finally:
            stop.set()
            t.join(timeout=10)
        assert not errors, f"reader observed a torn database: {errors}"

    def test_unknown_knob_rejected(self):
        with pytest.raises(tdb.TuningDBError):
            tdb.TuningEntry(
                key=tdb.current_key(8, 8, jnp.float32, "onemax"),
                knobs={"pallas_bogus": 1},
            )


# ------------------------------------------------------------- resolution


class TestResolution:
    def test_precedence_user_beats_db_beats_default(self):
        entry = _entry(knobs={
            "pallas_deme_size": 256, "pallas_layout": "riffle",
            "pallas_subblock": None,
        })
        cfg = PGAConfig(pallas_deme_size=512)  # explicit user knob
        knobs, prov = tdb.resolve_config_knobs(cfg, entry)
        assert knobs["pallas_deme_size"] == 512
        assert prov["pallas_deme_size"] == "user"
        assert knobs["pallas_layout"] == "riffle"
        assert prov["pallas_layout"] == "db"
        assert knobs["pallas_subblock"] is None
        assert prov["pallas_subblock"] == "default"

    def test_no_entry_is_provenance_free(self):
        knobs, prov = tdb.resolve_config_knobs(PGAConfig(), None)
        assert prov is None
        assert all(v is None for v in knobs.values())

    def test_engine_resolution_and_event(self, tmp_path):
        from libpga_tpu.utils import telemetry
        from libpga_tpu.utils.telemetry import TelemetryConfig

        path = _db_file(tmp_path, "t.json", _entry())
        set_tuning_db(path)
        events = str(tmp_path / "events.jsonl")
        pga = PGA(seed=0, config=PGAConfig(
            use_pallas=False,
            telemetry=TelemetryConfig(history_gens=0, events_path=events),
        ))
        pga.set_objective("onemax")
        pga.create_population(256, 16)
        deme, layout, subblock, prov = pga._resolved_pallas_knobs(256, 16)
        assert deme == 256 and prov["pallas_deme_size"] == "db"
        pga.run(2)
        records = telemetry.validate_log(events)
        tuned = [r for r in records if r["event"] == "tuned_config"]
        assert tuned and tuned[0]["knobs"]["pallas_deme_size"] == 256
        # once per (shape, knobs), not per run
        pga._resolved_pallas_knobs(256, 16)
        pga.run(2)
        records = telemetry.validate_log(events)
        assert len([
            r for r in records if r["event"] == "tuned_config"
        ]) == 1

    def test_db_none_is_byte_identical(self, tmp_path):
        """db=None lowers the EXACT StableHLO of a matched all-default
        entry: the resolution layer is host-side only. Compared through
        ``analysis.fingerprint`` — the shared canonical digest."""
        from libpga_tpu.analysis import fingerprint

        def lowered():
            pga = PGA(seed=0, config=PGAConfig(use_pallas=False))
            pga.set_objective("onemax")
            pga.create_population(128, 16)
            fn, _ = pga._compiled_run_meta(128, 16)
            k = jax.eval_shape(lambda: jax.random.key(0))
            return fingerprint(
                fn,
                jax.ShapeDtypeStruct((128, 16), jnp.float32),
                jax.ShapeDtypeStruct(k.shape, k.dtype),
                jax.ShapeDtypeStruct((), jnp.int32),
                jax.ShapeDtypeStruct((), jnp.float32),
                jax.ShapeDtypeStruct((1, 2), jnp.float32),
            )

        default_entry = _entry(pop=128, knobs={
            "pallas_deme_size": None, "pallas_layout": None,
            "pallas_subblock": None,
        })
        path = _db_file(tmp_path, "d.json", default_entry)
        set_tuning_db(path)
        with_db = lowered()
        set_tuning_db(None)
        without_db = lowered()
        assert with_db == without_db

    def test_env_var_transport(self, tmp_path, monkeypatch):
        """PGA_TUNING_DB — the fleet-worker transport — installs the DB
        lazily on first active_db() when nothing was set explicitly."""
        path = _db_file(tmp_path, "env.json", _entry())
        set_tuning_db(None)
        monkeypatch.setenv(tdb.ENV_VAR, path)
        tdb._ACTIVE.update(env_checked=False, db=None, path=None)
        db = tdb.active_db()
        assert db is not None and len(db) == 1
        assert tdb.active_path() == os.path.abspath(path)

    def test_fleet_config_carries_tuning_db(self):
        from libpga_tpu.config import FleetConfig

        assert FleetConfig(tuning_db="/x/t.json").tuning_db == "/x/t.json"


# ----------------------------------------------------- serving cache keys


class TestServingCacheKey:
    def test_tuned_signature_never_collides_with_untuned(self, tmp_path):
        from libpga_tpu.serving import BatchedRuns, RunRequest

        req = RunRequest(size=256, genome_len=16, n=2, seed=0)
        untuned_ex = BatchedRuns(
            "onemax", config=PGAConfig(use_pallas=False)
        )
        sig_untuned = untuned_ex.signature(req)
        path = _db_file(tmp_path, "t.json", _entry())
        set_tuning_db(path)
        tuned_ex = BatchedRuns(
            "onemax", config=PGAConfig(use_pallas=False)
        )
        sig_tuned = tuned_ex.signature(req)
        assert sig_tuned != sig_untuned
        assert ("tuned", None) in sig_untuned
        tail = dict([sig_tuned[-1]])["tuned"]
        assert ("pallas_deme_size", 256) in tail

    def test_warmup_records_provenance_and_event(self, tmp_path):
        from libpga_tpu.serving import BatchedRuns, RunRequest
        from libpga_tpu.serving import cache as scache
        from libpga_tpu.utils import telemetry

        path = _db_file(tmp_path, "t.json", _entry())
        set_tuning_db(path)
        events = str(tmp_path / "ev.jsonl")
        log = telemetry.EventLog(events)
        ex = BatchedRuns(
            "onemax", config=PGAConfig(use_pallas=False), events=log,
        )
        res = ex.run([RunRequest(size=256, genome_len=16, n=2, seed=0)])
        [r.block() for r in res]
        log.close()
        stats = scache.PROGRAM_CACHE.stats()
        mine = [
            t for t in stats.get("tuned", [])
            if t["population_size"] == 256 and t["genome_len"] == 16
            and t["db"] == os.path.abspath(path)
        ]
        assert mine and mine[0]["knobs"]["pallas_deme_size"] == 256
        assert mine[0]["provenance"]["pallas_deme_size"] == "db"
        records = telemetry.validate_log(events)
        assert any(r["event"] == "tuned_config" for r in records)


# ---------------------------------------------------------------- tuner


class TestTuner:
    def _settings(self):
        from libpga_tpu.tuning.tuner import TunerSettings

        return TunerSettings(
            budget=3, seed=11, ga_population=8, max_generations=3,
            rounds=2, min_rel_ci=0.5, max_rounds=3,
            measure_lo=2, measure_hi=5, measure_tries=1,
        )

    def test_autotune_deterministic_and_never_regresses(self, tmp_path):
        from libpga_tpu.tuning.tuner import autotune

        path = str(tmp_path / "t.json")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            e1 = autotune(256, 16, objective="onemax",
                          settings=self._settings(), db_path=path)
            e2 = autotune(256, 16, objective="onemax",
                          settings=self._settings(), db_path=path)
        assert e1.knobs == e2.knobs and e1.plan == e2.plan
        # CPU: one XLA plan → the default is recorded, by construction
        # never regressing it.
        assert e1.plan["path"] == "xla"
        assert e1.gens_per_sec >= e1.default_gens_per_sec * (1 - 0.04)
        loaded = tdb.TuningDB.load(path)
        assert loaded.lookup(e1.key).knobs == e1.knobs

    def test_compile_failure_scores_worst_not_crash(self):
        from libpga_tpu.tuning.tuner import (
            MeasurementOracle, TunerSettings,
        )

        ctx = space.SpaceContext(256, 16)
        oracle = MeasurementOracle(
            ctx, "onemax", self._settings(), use_pallas=None,
        )

        def boom(knobs):
            raise RuntimeError("injected build failure")

        oracle._make_runner = boom
        oracle._measure_wave([])
        rec = oracle.measured[oracle.default_key]
        assert rec["gens_per_sec"] == 0.0
        assert "injected build failure" in rec["error"]

    def test_oracle_rejects_inadmissible_without_compiling(self):
        from libpga_tpu.tuning.tuner import MeasurementOracle

        ctx = space.SpaceContext(256, 16)
        oracle = MeasurementOracle(
            ctx, "onemax", self._settings(), use_pallas=None,
        )
        # riffle + subblock is inadmissible (strict): fitness -1
        # without a measurement.
        row = np.zeros(4, np.float32)
        row[1] = 0.5   # layout -> "riffle"
        row[2] = 0.5   # subblock -> 2
        out = oracle.lookup_host(row[None, :])
        assert out[0] == -1.0
        assert not oracle.measured

    def test_capi_bridge_roundtrip(self, tmp_path):
        from libpga_tpu import capi_bridge as cb

        path = str(tmp_path / "t.json")
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            measured = cb.autotune(256, 16, "onemax", 2, path, 0)
        assert measured >= 1 and os.path.exists(path)
        assert cb.set_tuning_db(path) == 0
        assert tdb.active_path() == os.path.abspath(path)
        with pytest.raises(Exception):
            cb.set_tuning_db(str(tmp_path / "missing.json"))
        # failed install leaves the previous DB active
        assert tdb.active_path() == os.path.abspath(path)
        assert cb.set_tuning_db("") == 0
        assert tdb.active_db() is None
