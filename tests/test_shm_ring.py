"""Shared-memory ticket ring (ISSUE 18): framing, degradation, waits.

The ring is an accelerator, never a source of truth — these tests pin
the framing protocol (seqlock + CRC torn-write detection), every
degradation edge (torn records, CRC-bad frames, overflow, stale rings
left by a SIGKILL'd coordinator, injected write faults), and the
fleet-level contract that a broken ring only ever costs speed, never
results.
"""

import os
import struct
import subprocess
import sys
import threading
import time
import zlib

import pytest

from libpga_tpu.robustness import faults
from libpga_tpu.robustness.faults import FaultPlan
from libpga_tpu.serving.shm_ring import (
    HB_SLOTS,
    MUT_OFF,
    RING_FILENAME,
    RingError,
    ShmRing,
)


def ring_path(tmp_path):
    return str(tmp_path / RING_FILENAME)


def dead_pid():
    """A real pid guaranteed dead: a child that already exited."""
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait()
    return proc.pid


# ------------------------------------------------------------- lifecycle


class TestLifecycle:
    def test_create_attach_roundtrip(self, tmp_path):
        path = ring_path(tmp_path)
        ring, prior = ShmRing.create(path)
        assert prior == {"existed": False, "stale": False, "prev_pid": 0}
        ring.advertise("submit", "b0001")
        ring.advertise("submit", "b0002")
        ring.set_pending_depth(2)

        att = ShmRing.attach(path)
        mut = att.mutable()
        assert mut["head"] == 2 and mut["pending_depth"] == 2
        res = att.frames_since(0)
        assert [f["name"] for f in res["frames"]] == ["b0001", "b0002"]
        assert not res["overflowed"] and not res["torn"]
        att.close()
        ring.close(unlink=True)
        assert not os.path.exists(path)

    def test_attach_missing_and_truncated(self, tmp_path):
        with pytest.raises(RingError):
            ShmRing.attach(ring_path(tmp_path))
        path = ring_path(tmp_path)
        with open(path, "wb") as fh:
            fh.write(b"PGARING1 but far too short")
        with pytest.raises(RingError):
            ShmRing.attach(path)

    def test_attach_bad_magic_and_bad_slot(self, tmp_path):
        path = ring_path(tmp_path)
        ring, _ = ShmRing.create(path)
        ring.close()
        with open(path, "r+b") as fh:
            fh.write(b"NOTARING")
        with pytest.raises(RingError):
            ShmRing.attach(path)
        ring, _ = ShmRing.create(path)  # restores a valid header
        with pytest.raises(RingError):
            ShmRing.attach(path, slot=HB_SLOTS, worker_id="w0")
        ring.close(unlink=True)

    def test_unlink_is_owner_only(self, tmp_path):
        path = ring_path(tmp_path)
        ring, _ = ShmRing.create(path)
        att = ShmRing.attach(path)
        att.close(unlink=True)  # non-owner: must NOT remove the file
        assert os.path.exists(path)
        ring.close(unlink=True)
        assert not os.path.exists(path)


# ------------------------------------------------- stale-ring detection


class TestStaleRing:
    def test_live_predecessor_is_not_stale(self, tmp_path):
        path = ring_path(tmp_path)
        first, _ = ShmRing.create(path)  # header pid = us, alive
        first.close()
        second, prior = ShmRing.create(path)
        assert prior["existed"] and not prior["stale"]
        assert prior["prev_pid"] == os.getpid()
        second.close(unlink=True)

    def test_dead_coordinator_ring_is_stale_and_rebuilt(self, tmp_path):
        path = ring_path(tmp_path)
        first, _ = ShmRing.create(path)
        first.close()
        # Rewrite the header pid to a real-but-dead pid — exactly what
        # a SIGKILL'd coordinator leaves behind.
        gone = dead_pid()
        with open(path, "r+b") as fh:
            fh.seek(28)  # _FIXED_FMT: 8s + 5*I -> pid at offset 28
            fh.write(struct.pack("<Q", gone))
        peeked = ShmRing.peek(path)
        assert peeked["pid"] == gone and not peeked["coordinator_alive"]
        ring, prior = ShmRing.create(path)
        assert prior == {"existed": True, "stale": True, "prev_pid": gone}
        assert ring.mutable()["head"] == 0  # fresh image, old frames gone
        ring.close(unlink=True)

    def test_corrupt_ring_counts_as_stale(self, tmp_path):
        path = ring_path(tmp_path)
        with open(path, "wb") as fh:
            fh.write(os.urandom(128))
        ring, prior = ShmRing.create(path)
        assert prior["existed"] and prior["stale"]
        ring.close(unlink=True)


# --------------------------------------------------- framing/degradation


class TestFraming:
    def test_torn_mutable_record_reads_none(self, tmp_path):
        path = ring_path(tmp_path)
        ring, _ = ShmRing.create(path)
        assert ring.mutable() is not None
        # Force the seqlock odd = writer died mid-store.
        with open(path, "r+b") as fh:
            fh.seek(MUT_OFF)
            fh.write(struct.pack("<I", 1))
        att = ShmRing.attach(path)
        assert att.mutable() is None
        res = att.frames_since(0)
        assert res["torn"] and res["frames"] == []
        reason, _, _ = att.wait_pending(0, 0, timeout=0.05)
        assert reason == "torn"
        att.close()
        ring.close(unlink=True)

    def test_crc_bad_frame_is_skipped_and_flagged(self, tmp_path):
        path = ring_path(tmp_path)
        ring, _ = ShmRing.create(path, hb_slots=2, n_frames=8)
        ring.advertise("submit", "b0001")
        ring.advertise("submit", "b0002")
        off = ring._frame_off(1)
        # Flip a payload byte under frame 1: stamp still matches, CRC
        # must reject it.
        with open(path, "r+b") as fh:
            fh.seek(off + 16 + 4)
            byte = fh.read(1)
            fh.seek(off + 16 + 4)
            fh.write(bytes([byte[0] ^ 0xFF]))
        att = ShmRing.attach(path)
        res = att.frames_since(0)
        assert res["torn"]
        assert [f["name"] for f in res["frames"]] == ["b0002"]
        att.close()
        ring.close(unlink=True)

    def test_overflow_reports_and_clamps(self, tmp_path):
        path = ring_path(tmp_path)
        ring, _ = ShmRing.create(path, hb_slots=2, n_frames=4)
        for i in range(10):
            ring.advertise("submit", f"b{i:04d}")
        res = ring.frames_since(0)  # 10 behind a 4-frame ring
        assert res["overflowed"]
        assert [f["name"] for f in res["frames"]] == [
            "b0006", "b0007", "b0008", "b0009"
        ]
        fresh = ring.frames_since(res["head"])
        assert fresh["frames"] == [] and not fresh["overflowed"]
        ring.close(unlink=True)

    def test_rebuild_under_reader_reports_overflow(self, tmp_path):
        path = ring_path(tmp_path)
        ring, _ = ShmRing.create(path)
        for i in range(5):
            ring.advertise("submit", f"b{i:04d}")
        ring.close()
        rebuilt, _ = ShmRing.create(path)  # head snapped back to 0
        res = rebuilt.frames_since(5)
        assert res["overflowed"]  # head < last_seq -> spool scan
        rebuilt.close(unlink=True)

    def test_oversized_payload_is_rejected(self, tmp_path):
        ring, _ = ShmRing.create(ring_path(tmp_path))
        with pytest.raises(RingError):
            ring.advertise("submit", "x" * (ring.frame_capacity() + 1))
        ring.close(unlink=True)


# ------------------------------------------------------- slots/counters


class TestSlots:
    def test_heartbeat_and_notify_counters(self, tmp_path):
        path = ring_path(tmp_path)
        ring, _ = ShmRing.create(path)
        w0 = ShmRing.attach(path, slot=0, worker_id="w0")
        w1 = ShmRing.attach(path, slot=1, worker_id="w1")
        before = w0.slot(0)["hb"]
        w0.note_claim()
        w0.note_publish()
        w1.heartbeat()
        assert w0.slot(0)["hb"] >= before
        counters = ring.counters()
        assert counters["claims"] == 1 and counters["publishes"] == 1
        assert counters["notify"] == 2 and counters["torn"] == 0
        total, torn = ring.notify_sum()
        assert total == 2 and torn == 0
        recs = {r["wid"]: r for r in ring.slots()}
        assert set(recs) == {"w0", "w1"}
        assert recs["w0"]["slot"] == 0 and recs["w0"]["pid"] == os.getpid()
        w0.close()
        w1.close()
        ring.close(unlink=True)

    def test_unbound_attach_cannot_write_slot(self, tmp_path):
        path = ring_path(tmp_path)
        ring, _ = ShmRing.create(path)
        att = ShmRing.attach(path)
        with pytest.raises(RingError):
            att.heartbeat()
        att.close()
        ring.close(unlink=True)


# ---------------------------------------------------------------- waits


class TestWaits:
    def test_wait_pending_wakes_on_head(self, tmp_path):
        path = ring_path(tmp_path)
        ring, _ = ShmRing.create(path)
        att = ShmRing.attach(path)
        t = threading.Timer(0.05, lambda: ring.advertise("submit", "b1"))
        t.start()
        t0 = time.monotonic()
        reason, head, _ = att.wait_pending(0, 0, timeout=5.0)
        waited = time.monotonic() - t0
        assert reason == "head" and head == 1
        assert waited < 2.0  # event wake, not timeout expiry
        att.close()
        ring.close(unlink=True)

    def test_wait_pending_wakes_on_depth_growth_only(self, tmp_path):
        path = ring_path(tmp_path)
        ring, _ = ShmRing.create(path)
        ring.set_pending_depth(3)
        att = ShmRing.attach(path)
        # Depth 3 already observed: an unchanged stale depth must NOT
        # wake (a worker that failed to claim would hot-spin).
        reason, _, depth = att.wait_pending(0, 3, timeout=0.05)
        assert reason == "timeout"
        ring.set_pending_depth(4)
        reason, _, depth = att.wait_pending(0, 3, timeout=5.0)
        assert reason == "depth" and depth == 4
        att.close()
        ring.close(unlink=True)

    def test_wait_pending_stop_event(self, tmp_path):
        ring, _ = ShmRing.create(ring_path(tmp_path))
        stop = threading.Event()
        threading.Timer(0.05, stop.set).start()
        reason, _, _ = ring.wait_pending(0, 0, timeout=5.0, stop=stop)
        assert reason == "stop"
        ring.close(unlink=True)

    def test_wait_activity_wakes_on_notify(self, tmp_path):
        path = ring_path(tmp_path)
        ring, _ = ShmRing.create(path)
        w0 = ShmRing.attach(path, slot=0, worker_id="w0")
        threading.Timer(0.05, w0.note_publish).start()
        reason, new_sum = ring.wait_activity(0, timeout=5.0)
        assert reason == "notify" and new_sum == 1
        reason, _ = ring.wait_activity(1, timeout=0.05)
        assert reason == "timeout"
        w0.close()
        ring.close(unlink=True)


# ------------------------------------------------------- injected faults


class TestInjectedFaults:
    def test_publish_fault_raises_from_write_sites(self, tmp_path):
        ring, _ = ShmRing.create(ring_path(tmp_path))
        with faults.active(FaultPlan("ring.publish", probability=1.0,
                                     times=None)):
            with pytest.raises(faults.InjectedFault):
                ring.advertise("submit", "b1")
            with pytest.raises(faults.InjectedFault):
                ring.set_pending_depth(1)
        ring.close(unlink=True)

    def test_wake_fault_raises_from_waits(self, tmp_path):
        ring, _ = ShmRing.create(ring_path(tmp_path))
        with faults.active(FaultPlan("ring.wake", probability=1.0,
                                     times=None)):
            with pytest.raises(faults.InjectedFault):
                ring.wait_activity(0, timeout=0.01)
            with pytest.raises(faults.InjectedFault):
                ring.wait_pending(0, 0, timeout=0.01)
        ring.close(unlink=True)


# ------------------------------------------------ fleet-level degradation


class TestFleetDegradation:
    """The contract the whole module exists to honor: any ring failure
    degrades to the pure-spool path with identical results."""

    def _run_fleet(self, tmp_path, **fleet_kw):
        from libpga_tpu.config import FleetConfig, PGAConfig
        from libpga_tpu.serving.fleet import Fleet, FleetTicket

        events = []
        spool = str(tmp_path / "spool")
        fcfg = FleetConfig(
            n_workers=1, max_batch=1, max_wait_ms=5, poll_s=0.05,
            lease_timeout_s=10.0, heartbeat_s=0.2, **fleet_kw
        )

        class Cap:
            def emit(self, kind, **fields):
                events.append((kind, fields))

            def close(self):
                pass

        fleet = Fleet(spool, "onemax", PGAConfig(seed=3), fcfg, events=Cap())
        fleet.start()
        try:
            h = fleet.submit(FleetTicket(size=32, genome_len=8, n=2, seed=1))
            result = h.result(timeout=90)
        finally:
            fleet.close()
        return result, events, fleet

    @pytest.mark.slow
    def test_coordinator_publish_fault_degrades_not_fails(self, tmp_path):
        with faults.active(FaultPlan("ring.publish", at_call_n=1)):
            result, events, fleet = self._run_fleet(tmp_path)
        kinds = [k for k, _ in events]
        assert "ring_degraded" in kinds
        degraded = dict(events[kinds.index("ring_degraded")][1])
        assert degraded["role"] == "coordinator"
        assert result.best_score is not None  # work still completed

    @pytest.mark.slow
    def test_ring_off_config_runs_pure_spool(self, tmp_path):
        result, events, fleet = self._run_fleet(tmp_path, ring=False)
        kinds = [k for k, _ in events]
        assert "ring_attach" not in kinds
        assert result.best_score is not None
        assert not os.path.exists(
            os.path.join(str(tmp_path / "spool"), RING_FILENAME)
        )

    @pytest.mark.slow
    def test_stale_ring_rebuilt_on_fleet_start(self, tmp_path):
        spool = tmp_path / "spool"
        spool.mkdir()
        path = str(spool / RING_FILENAME)
        first, _ = ShmRing.create(path)
        first.close()
        gone = dead_pid()
        with open(path, "r+b") as fh:
            fh.seek(28)
            fh.write(struct.pack("<Q", gone))
        result, events, fleet = self._run_fleet(tmp_path)
        attach = [f for k, f in events if k == "ring_attach"
                  and f.get("role") == "coordinator"]
        assert attach and attach[0]["stale_replaced"] is True
        assert result.best_score is not None


def test_fleet_config_ring_validation():
    from libpga_tpu.config import FleetConfig

    assert FleetConfig().ring is True
    with pytest.raises(ValueError):
        FleetConfig(ring_fallback_s=0.0)
    with pytest.raises(ValueError):
        FleetConfig(ring_fallback_s=-1.0)
