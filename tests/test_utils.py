"""Tests for the auxiliary subsystems: profiling hooks and automatic
checkpointing (both new capabilities — the reference's observability is a
single printf and its recovery story is exit-on-error, survey §5)."""

import numpy as np

from libpga_tpu import PGA, PGAConfig
from libpga_tpu.utils import checkpoint, profiling


def _solver(seed=0, pop=64, length=8):
    pga = PGA(seed=seed, config=PGAConfig())
    handle = pga.create_population(pop, length)
    pga.set_objective("onemax")
    return pga, handle


def test_timed_runs_logs_every_run():
    pga, _ = _solver()
    lines = []
    with profiling.timed_runs(pga, log=lines.append) as metrics:
        pga.run(3)
        pga.run(2)
    assert len(lines) == 2
    assert "3 gens" in lines[0] and "gens/sec" in lines[0]
    assert metrics.total_generations == 5
    # restored: no more logging outside the block
    pga.run(1)
    assert len(lines) == 2


def test_trace_writes_profile(tmp_path):
    pga, _ = _solver()
    with profiling.trace(str(tmp_path)):
        pga.run(2)
    # jax writes trace artifacts under plugins/profile/<ts>/
    assert any(tmp_path.rglob("*")), "no trace output written"


def test_auto_checkpointer_saves_and_resumes(tmp_path):
    path = str(tmp_path / "state.npz")
    pga, handle = _solver(seed=7)
    ckpt = checkpoint.AutoCheckpointer(pga, path, every_generations=5)
    pga.run(3)  # below threshold: no save yet
    assert not (tmp_path / "state.npz").exists()
    pga.run(3)  # crosses 5: saves
    assert (tmp_path / "state.npz").exists()
    saved_best = pga.get_best(handle).copy()
    pga.run(4)  # not yet re-saved (4 < 5)
    ckpt.close()  # final save

    fresh = PGA(seed=99, config=PGAConfig())
    fresh.set_objective("onemax")
    checkpoint.restore(fresh, path)
    from libpga_tpu.engine import PopulationHandle

    restored_best = fresh.get_best(PopulationHandle(0))
    # close() saved the final state, which includes the last run
    assert fresh.num_populations == 1
    assert restored_best.shape == saved_best.shape
    np.testing.assert_array_equal(
        restored_best, pga.get_best(handle)
    )
