"""Tests for the auxiliary subsystems: profiling hooks and automatic
checkpointing (both new capabilities — the reference's observability is a
single printf and its recovery story is exit-on-error, survey §5)."""

import numpy as np

from libpga_tpu import PGA, PGAConfig
from libpga_tpu.utils import checkpoint, profiling


def _solver(seed=0, pop=64, length=8):
    pga = PGA(seed=seed, config=PGAConfig())
    handle = pga.create_population(pop, length)
    pga.set_objective("onemax")
    return pga, handle


def test_timed_runs_logs_every_run():
    pga, _ = _solver()
    lines = []
    with profiling.timed_runs(pga, log=lines.append) as metrics:
        pga.run(3)
        pga.run(2)
    assert len(lines) == 2
    assert "3 gens" in lines[0] and "gens/sec" in lines[0]
    assert metrics.total_generations == 5
    # restored: no more logging outside the block
    pga.run(1)
    assert len(lines) == 2


def test_trace_writes_profile(tmp_path):
    pga, _ = _solver()
    with profiling.trace(str(tmp_path)):
        pga.run(2)
    # jax writes trace artifacts under plugins/profile/<ts>/
    assert any(tmp_path.rglob("*")), "no trace output written"


def test_listener_add_remove_and_isolation():
    """Listener registry: add/remove round-trips, and a raising listener
    cannot abort the run that notifies it — it warns, later listeners
    still fire, and the record is kept (satellite fix: one bad logger
    used to propagate out of PGA.run AFTER the run completed)."""
    import warnings

    from libpga_tpu.utils.metrics import Metrics

    m = Metrics()
    seen = []

    def bad(rec):
        raise RuntimeError("boom")

    def good(rec):
        seen.append(rec)

    m.add_listener(bad)
    m.add_listener(good)
    m.on_run = bad
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        rec = m.record_run(3, 10, 0.5)
    assert len(seen) == 1 and seen[0] is rec
    assert len(m.runs) == 1
    assert sum("boom" in str(x.message) for x in w) == 2  # listener + on_run
    # removal: no further notifications; removing twice is a no-op
    m.remove_listener(bad)
    m.remove_listener(good)
    m.remove_listener(good)
    m.on_run = None
    m.record_run(1, 10, 0.5)
    assert len(seen) == 1


def test_raising_listener_does_not_abort_engine_run():
    pga, _ = _solver()
    pga.metrics.add_listener(
        lambda rec: (_ for _ in ()).throw(RuntimeError("observer bug"))
    )
    import warnings

    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        assert pga.run(2) == 2  # the run survives its observer


def test_generations_per_sec_zero_seconds_is_zero():
    """A sub-resolution timer must read 0.0 gens/sec, not inf (satellite
    fix: inf poisoned aggregates over records)."""
    from libpga_tpu.utils.metrics import Metrics, RunRecord

    rec = RunRecord(generations=5, population_size=10, seconds=0.0,
                    timestamp=0.0)
    assert rec.generations_per_sec == 0.0
    assert Metrics().generations_per_sec == 0.0


def test_interleaved_medians_counts_dropped_samples():
    """Degenerate (NaN) samples are excluded AND accounted: the result
    carries per-runner n/dropped and a warning names the shrunken n
    (satellite fix: silently dropping samples hid how weak a median
    was)."""
    import warnings

    # sample() pulls from each runner's scripted sequence; runner "a"
    # hits one degenerate round.
    vals = {"a": iter([1.0, float("nan"), 3.0]), "b": iter([2.0, 2.0, 2.0])}
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        med = profiling.interleaved_medians(
            {"a": "a", "b": "b"}, rounds=3,
            sample=lambda name: next(vals[name]),
        )
    assert med["a"] == 2.0 and med["b"] == 2.0
    assert med.n == {"a": 2, "b": 3}
    assert med.dropped == {"a": 1, "b": 0}
    assert any("n=2 of 3" in str(x.message) for x in w)


def test_interleaved_medians_repeat_until_confidence():
    """min_rel_ci extends the interleave with FULL rounds until every
    runner's half-IQR/median is at or under the target, bounded by
    max_rounds; .n/.dropped count over ALL executed rounds and .rel_ci
    states the achieved confidence (ISSUE 10 satellite)."""
    # Runner "noisy" needs extra rounds to tighten; "tight" is
    # constant from the start.
    seqs = {
        "noisy": iter([10.0, 20.0, 15.0, 15.1, 15.0, 15.0, 15.0, 15.0]),
        "tight": iter([5.0] * 8),
    }
    med = profiling.interleaved_medians(
        {"noisy": "noisy", "tight": "tight"}, rounds=2,
        min_rel_ci=0.05, max_rounds=8,
        sample=lambda name: next(seqs[name]),
    )
    assert med.rounds > 2, "confidence mode never extended"
    assert med.rel_ci["noisy"] <= 0.05
    assert med.rel_ci["tight"] == 0.0
    assert med.n["noisy"] == med.rounds and med.dropped["noisy"] == 0
    assert med["tight"] == 5.0


def test_interleaved_medians_max_rounds_bounds_noise():
    """A runner that never converges stops at max_rounds with an
    honest wide rel_ci instead of looping forever."""
    import itertools

    flip = itertools.cycle([1.0, 100.0])
    med = profiling.interleaved_medians(
        {"wild": "wild"}, rounds=2, min_rel_ci=0.01, max_rounds=5,
        sample=lambda name: next(flip),
    )
    assert med.rounds == 5
    assert med.rel_ci["wild"] > 0.01
    assert med.n["wild"] == 5


def test_interleaved_medians_default_mode_unchanged():
    """Without min_rel_ci the protocol is exactly the old one: the
    requested rounds, no extension (max_rounds defaults to rounds)."""
    seq = iter([1.0, 2.0, 3.0])
    med = profiling.interleaved_medians(
        {"a": "a"}, rounds=3, sample=lambda name: next(seq),
    )
    assert med.rounds == 3 and med["a"] == 2.0


def test_auto_checkpointer_saves_and_resumes(tmp_path):
    path = str(tmp_path / "state.npz")
    pga, handle = _solver(seed=7)
    ckpt = checkpoint.AutoCheckpointer(pga, path, every_generations=5)
    pga.run(3)  # below threshold: no save yet
    assert not (tmp_path / "state.npz").exists()
    pga.run(3)  # crosses 5: saves
    assert (tmp_path / "state.npz").exists()
    saved_best = pga.get_best(handle).copy()
    pga.run(4)  # not yet re-saved (4 < 5)
    ckpt.close()  # final save

    fresh = PGA(seed=99, config=PGAConfig())
    fresh.set_objective("onemax")
    checkpoint.restore(fresh, path)
    from libpga_tpu.engine import PopulationHandle

    restored_best = fresh.get_best(PopulationHandle(0))
    # close() saved the final state, which includes the last run
    assert fresh.num_populations == 1
    assert restored_best.shape == saved_best.shape
    np.testing.assert_array_equal(
        restored_best, pga.get_best(handle)
    )
