"""Regression: builtin one_point/arithmetic crossovers must not fall
back silently to the XLA path.

Before this round, ``engine._crossover_kind`` returned None for both —
one plain setter call (``pga.set_crossover(one_point_crossover)``)
silently cost ~10× at headline scale. They now route through fused
expression equivalents (``engine._CROSSOVER_EXPRS``), and operators
that genuinely CANNOT run in-kernel produce a documented warning
instead of nothing.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libpga_tpu import PGA, PGAConfig
from libpga_tpu.ops.crossover import (
    arithmetic_crossover,
    one_point_crossover,
)


def _interpret():
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.force_tpu_interpret_mode()


@pytest.mark.parametrize("op", [one_point_crossover, arithmetic_crossover])
def test_builtin_crossover_has_kernel_kind(op):
    pga = PGA(seed=0)
    pga.set_crossover(op)
    kind = pga._crossover_kind()
    assert kind is not None, "silent XLA fallback regressed"
    assert getattr(kind, "kernel_rows", None) is not None
    # cached: repeated gate checks must reuse ONE compiled operator
    assert pga._crossover_kind() is kind


@pytest.mark.parametrize("op", [one_point_crossover, arithmetic_crossover])
def test_pallas_gate_accepts_builtin_crossovers(op, monkeypatch):
    pga = PGA(seed=0, config=PGAConfig(use_pallas=True))
    monkeypatch.setattr(pga, "_pallas_backend_ok", lambda: True)
    pga.set_crossover(op)
    assert pga._pallas_gate(), "gate must pass for routed builtins"


def test_one_point_expression_matches_builtin_semantics():
    """The expression equivalent and the builtin compute the same child
    for the same cut draw (the builtin reads rand[0], the expression
    the per-row stream q — identical distribution, identical decode)."""
    pga = PGA(seed=0)
    kind = pga._crossover_expr_equivalent("one_point")
    P, L = 4, 16
    k1, k2 = jax.random.split(jax.random.key(3))
    p1 = jax.random.uniform(k1, (P, L))
    p2 = jax.random.uniform(k2, (P, L))
    cut = jnp.full((P, 1), 0.37)
    zero = jnp.zeros((P, L))
    expr_child = kind.kernel_rows(p1, p2, zero, zero, cut, cut)
    rand = jnp.concatenate([cut, jnp.zeros((P, L - 1))], axis=1)
    builtin_child = one_point_crossover.batched(p1, p2, rand)
    np.testing.assert_allclose(
        np.asarray(expr_child), np.asarray(builtin_child), atol=1e-7
    )


def test_arithmetic_expression_matches_builtin_semantics():
    pga = PGA(seed=0)
    kind = pga._crossover_expr_equivalent("arithmetic")
    P, L = 4, 16
    k1, k2, k3 = jax.random.split(jax.random.key(4), 3)
    p1 = jax.random.uniform(k1, (P, L))
    p2 = jax.random.uniform(k2, (P, L))
    r = jax.random.uniform(k3, (P, L))
    zero = jnp.zeros((P, L))
    q = jnp.zeros((P, 1))
    expr_child = kind.kernel_rows(p1, p2, r, zero, q, q)
    np.testing.assert_allclose(
        np.asarray(expr_child),
        np.asarray(arithmetic_crossover.batched(p1, p2, r)),
        atol=1e-6,
    )


def test_one_point_kind_lowers_in_kernel():
    """The routed kind actually builds and runs the fused kernel
    (interpret mode; zero PRNG bits → cut 0 → every child is its
    deme's rank-0 row verbatim at mutation rate 0)."""
    from libpga_tpu.ops.pallas_step import make_pallas_breed

    P, L, K = 512, 16, 128
    pga = PGA(seed=0)
    kind = pga._crossover_expr_equivalent("one_point")
    with _interpret():
        breed = make_pallas_breed(
            P, L, deme_size=K, crossover_kind=kind, mutation_rate=0.0,
        )
        assert breed is not None
        genomes = jax.random.uniform(jax.random.key(5), (P, L))
        scores = -(jnp.arange(P, dtype=jnp.float32) % K)  # rank0 = deme row 0
        out = np.asarray(breed(genomes, scores, jax.random.key(0)))
    G = P // K
    gen = np.asarray(genomes)
    for r in (0, 1, K - 1):
        for g in range(G):
            # atol covers the f32 hi/lo selection matmul's documented
            # ~1e-5 reconstruction error (ops/pallas_step.py docstring).
            np.testing.assert_allclose(
                out[r * G + g], gen[g * K], atol=5e-5,
                err_msg=f"r={r} g={g}",
            )


def test_custom_crossover_warns_instead_of_silent_fallback(monkeypatch):
    pga = PGA(seed=7, config=PGAConfig(use_pallas=True))
    pga.create_population(128, 8)
    pga.set_objective("onemax")
    pga.set_crossover(lambda p1, p2, r: jnp.where(r > 0.5, p1, p2))
    monkeypatch.setattr(pga, "_pallas_backend_ok", lambda: True)
    with pytest.warns(UserWarning, match="no in-kernel form"):
        pga.run(2)


def test_builtin_crossover_run_does_not_warn(monkeypatch):
    """The routed builtins must NOT trigger the fallback warning — but
    off-TPU the factory still declines at build, so only the warning
    path is pinned here (the kernel path itself is covered above)."""
    import warnings

    pga = PGA(seed=7, config=PGAConfig(use_pallas=True))
    pga.create_population(128, 8)
    pga.set_objective("onemax")
    pga.set_crossover(one_point_crossover)
    monkeypatch.setattr(pga, "_pallas_backend_ok", lambda: True)
    with warnings.catch_warnings():
        warnings.simplefilter("error", UserWarning)
        pga._warn_xla_fallback()  # must be a no-op for routed builtins
