"""Tests for the fused Pallas generation step (ops/pallas_step.py).

The kernel's PRNG (``pltpu.prng_random_bits``) only produces real entropy
on TPU hardware; under ``force_tpu_interpret_mode`` on CPU it yields
all-zero bits. That still deterministically exercises everything
*structural* — block mappings, the riffle-shuffle output layout, the
one-hot selection matmuls, padding — because zero bits mean "the sampled
winner rank is 0", i.e. every child descends from its deme's BEST-scoring
row. Structure tests feed strictly-decreasing in-deme scores
(``deme_rank0_scores``) so that row is deme row 0 deterministically (score
ties are shuffled randomly per generation since round 3), giving an
exactly predictable output.
Distributional properties (selection pressure, mutation statistics) are
validated on real TPU by ``tools/tpu_kernel_checks.py``, which the
benchmark path runs against hardware.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libpga_tpu.ops.pallas_step import make_pallas_breed, make_pallas_run
from libpga_tpu.objectives import onemax


def _interpret():
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.force_tpu_interpret_mode()


def deme_rank0_scores(P, K):
    """Strictly decreasing scores within every deme (row d·K+j scores
    -j): rank 0 is deme row 0 with no ties, so the per-generation random
    score-tie shuffle cannot fire and zero-PRNG-bits structure
    expectations ("every child copies deme row 0") stay exact."""
    return -(jnp.arange(P, dtype=jnp.float32) % K)


def test_unsupported_shapes_return_none():
    # sub-tile populations (under one 128-row deme) stay on the XLA path
    assert make_pallas_breed(100, 10, deme_size=256) is None
    # anything >= 128 is served, via internal padding when necessary
    breed = make_pallas_breed(1000, 10, deme_size=256)
    assert breed is not None and breed.Pp == 1024


def test_deme_size_auto_fallback():
    """An undivisible or invalid preferred deme size falls back to a
    power-of-two divisor (zero padding) or, failing that, to padding the
    population up to a deme multiple."""
    from libpga_tpu.ops.pallas_step import _pick_deme_size

    assert _pick_deme_size(1 << 20, 256) == 256
    assert _pick_deme_size(1 << 20, 96) == 1024  # invalid preferred -> largest
    assert _pick_deme_size(40_960, 256) == 256
    assert _pick_deme_size(128 * 3, 256) == 128  # only 128 divides exactly
    assert _pick_deme_size(1000, 256) == 256  # padded to 1024 (tie -> preferred)
    assert _pick_deme_size(40_000, 256) == 256  # 192 pad rows: negligible
    # egregious padding loses to a lean fit: 1100 at K=256 wastes 16%
    # (180/1100) vs 4.7% at K=128
    assert _pick_deme_size(1100, 256) == 128
    assert _pick_deme_size(100, 256) is None  # sub-tile
    # degenerate tails are rejected, not served: 1025 = 4*256 + 1 would
    # breed 256 clones of the tail's single row every generation
    assert _pick_deme_size(1025, 256) is None
    assert make_pallas_breed(1025, 10, deme_size=256) is None
    # power-of-two but out-of-range preferred sizes are clamped to the
    # documented [128, 1024] band, not accepted verbatim (tiny demes
    # collapse tournament-2 toward cloning; advisor round-1 finding)
    assert _pick_deme_size(1 << 20, 2) == 1024
    assert _pick_deme_size(1 << 20, 64) == 1024
    assert _pick_deme_size(1 << 20, 2048) == 1024
    assert make_pallas_breed(1024, 10, deme_size=96) is not None


def test_engine_mutation_rate_from_raw_partial():
    """A raw functools.partial(point_mutate, rate=r) passes the
    default-operator gate; the engine must surface r (via .keywords), not
    silently fall back to the config default (advisor round-1 finding)."""
    from functools import partial

    from libpga_tpu import PGA
    from libpga_tpu.ops.mutate import make_point_mutate, point_mutate

    pga = PGA(seed=0)
    pga.set_mutate(partial(point_mutate, rate=0.42))
    assert pga._mutate_kind() == "point"
    assert pga._mutation_rate() == 0.42
    pga.set_mutate(make_point_mutate(0.13))
    assert pga._mutation_rate() == 0.13
    pga.set_mutate(None)
    assert pga._mutation_rate() == pga.config.mutation_rate


def test_engine_gaussian_params_follow_signature_defaults():
    """A bare partial(gaussian_mutate) executes at the operator's own
    signature defaults, so the kernel params must be read from the
    signature, not from literal copies that can drift (advisor round-2
    finding)."""
    import inspect
    from functools import partial

    import numpy as np

    from libpga_tpu import PGA
    from libpga_tpu.ops.mutate import gaussian_mutate

    sig = inspect.signature(gaussian_mutate).parameters
    pga = PGA(seed=0)
    pga.set_mutate(partial(gaussian_mutate))
    assert pga._mutate_kind() == "gaussian"
    np.testing.assert_allclose(
        np.asarray(pga._mutate_params())[0],
        [sig["rate"].default, sig["sigma"].default],
    )
    pga.set_mutate(partial(gaussian_mutate, rate=0.3, sigma=0.05))
    np.testing.assert_allclose(
        np.asarray(pga._mutate_params())[0], [0.3, 0.05], rtol=1e-6
    )


def test_run_factory_tournament_size_bounds():
    """k-way tournaments are served in-kernel up to the documented k=16
    contract bound; sizes outside it decline to the XLA path."""
    assert make_pallas_breed(1024, 10, tournament_size=0) is None
    assert make_pallas_breed(1024, 10, tournament_size=17) is None
    assert make_pallas_breed(1024, 10, tournament_size=3) is not None


def test_tournament_size_no_longer_shrinks_deme():
    """Rank-space selection holds one (K,K) rank cube regardless of k, so
    large tournaments keep the full deme (the former candidate-mask
    budget capped k=4 at K=512 and k=16 at K=256)."""
    for k in (2, 4, 16):
        b = make_pallas_breed(1 << 20, 10, deme_size=1024, tournament_size=k)
        assert b is not None and b.K == 1024, k


def test_kernel_structure_tournament_k3():
    """Zero PRNG bits with k=3 (a non-power-of-two, exercising the
    exp/log branch of the inverse-CDF sampler): the sampled winner rank
    is 0 and scores are equal, so the deme-row-0 child structure must
    hold."""
    P, L, K = 512, 12, 128
    G = P // K
    with _interpret():
        breed = make_pallas_breed(
            P, L, deme_size=K, mutation_rate=0.0, tournament_size=3
        )
        genomes = (
            jnp.broadcast_to(jnp.arange(P, dtype=jnp.float32)[:, None], (P, L))
            / P
        )
        out = np.asarray(
            breed(genomes, deme_rank0_scores(P, K), jax.random.key(0))
        )
    expect = np.asarray([((r % G) * K) / P for r in range(P)], np.float32)
    np.testing.assert_allclose(
        out, np.broadcast_to(expect[:, None], (P, L)), atol=2e-5, rtol=0
    )


@pytest.mark.skipif(
    jax.default_backend() == "tpu", reason="gate only applies off-TPU"
)
def test_run_factory_gates_on_backend():
    """Off-TPU the run factory must decline entirely — an explicit
    use_pallas=True falls back instead of crashing at Mosaic trace time."""
    assert make_pallas_run(onemax, tournament_size=2) is None


def test_kernel_structure_zero_bits():
    """With zero PRNG bits every child is deme-row-0 crossed with itself:
    output row r must be a copy of row 0 of deme ``r % G`` — this pins the
    input block mapping, the shuffle output mapping, and padding at once."""
    P, L, K = 1024, 20, 128
    G = P // K
    with _interpret():
        breed = make_pallas_breed(P, L, deme_size=K, mutation_rate=0.0)
        assert breed is not None
        genomes = (
            jnp.broadcast_to(jnp.arange(P, dtype=jnp.float32)[:, None], (P, L))
            / P
        )
        out = np.asarray(
            breed(genomes, deme_rank0_scores(P, K), jax.random.key(0))
        )
    assert out.shape == (P, L)
    expect = np.asarray([(r % G) * K / P for r in range(P)], dtype=np.float32)
    np.testing.assert_allclose(out, np.broadcast_to(expect[:, None], (P, L)))


def test_kernel_gene_values_near_exact():
    """The bf16 hi/lo one-hot matmul reproduces f32 genes to the documented
    ~1e-5 bound (hi+lo covers ~16 mantissa bits; residual ≤ ~2^-17 on
    [0,1) genes)."""
    P, L, K = 512, 130, 128  # L > 128 exercises multi-lane padding
    G = P // K
    key = jax.random.key(3)
    genomes = jax.random.uniform(key, (P, L), dtype=jnp.float32)
    with _interpret():
        breed = make_pallas_breed(P, L, deme_size=K, mutation_rate=0.0)
        out = np.asarray(
            breed(genomes, deme_rank0_scores(P, K), jax.random.key(1))
        )
    gn = np.asarray(genomes)
    # zero bits -> child r = row 0 of deme r % G
    for r in range(0, P, 37):
        src = (r % G) * K
        np.testing.assert_allclose(out[r], gn[src], atol=2e-5, rtol=0)


@pytest.mark.skipif(
    jax.default_backend() == "tpu", reason="auto-off only applies off-TPU"
)
def test_engine_falls_back_when_pallas_unavailable():
    """On CPU the auto setting disables Pallas and the XLA path runs."""
    from libpga_tpu import PGA, PGAConfig

    pga = PGA(seed=0, config=PGAConfig())
    assert pga.config.pallas_enabled() is False
    pop = pga.create_population(256, 8)
    pga.set_objective("onemax")
    pga.run(3)
    best = pga.get_best(pop)
    assert best.shape == (8,)


def test_kernel_padded_population_structure():
    """A population with no power-of-two deme divisor (here 300 = 128·2 +
    44) pads internally to G·K rows; with zero PRNG bits each child is
    deme-row-0, exactly as in the unpadded case, and only P rows come
    back."""
    P, L, K = 300, 12, 128
    with _interpret():
        breed = make_pallas_breed(P, L, deme_size=K, mutation_rate=0.0)
        assert breed is not None
        G = breed.Pp // K
        assert breed.Pp == 384 and G == 3
        genomes = (
            jnp.broadcast_to(jnp.arange(P, dtype=jnp.float32)[:, None], (P, L))
            / P
        )
        out = np.asarray(
            breed(genomes, deme_rank0_scores(P, K), jax.random.key(0))
        )
    assert out.shape == (P, L)
    expect = np.asarray([((r % G) * K) / P for r in range(P)], np.float32)
    # atol: gene values ride the bf16 hi/lo one-hot matmul (~1e-5 bound);
    # unlike the unpadded structure test, these genes are not dyadic.
    np.testing.assert_allclose(
        out, np.broadcast_to(expect[:, None], (P, L)), atol=2e-5, rtol=0
    )


def test_kernel_padded_fused_scores_inert_tail():
    """Fused evaluation on a padded population: returned scores match the
    returned genomes row-for-row, and the run loop contract (tail masked
    to -inf) holds for the padded variant."""
    from libpga_tpu.objectives import onemax

    P, L, K = 300, 12, 128
    with _interpret():
        breed = make_pallas_breed(
            P, L, deme_size=K, mutation_rate=0.0,
            fused_obj=onemax.kernel_rowwise,
        )
        genomes = jax.random.uniform(jax.random.key(2), (P, L))
        scores = jnp.zeros((P,), jnp.float32)
        g2, s2 = breed(genomes, scores, jax.random.key(0))
        # padded variant: feed (Pp, Lp)/(Pp,) directly, check the tail
        Pp, Lp = breed.Pp, breed.Lp
        gp = jnp.pad(genomes, ((0, Pp - P), (0, Lp - L)))
        sp = jnp.pad(scores, (0, Pp - P), constant_values=-jnp.inf)
        gp2, sp2 = breed.padded(gp, sp, jax.random.key(0))
    g2, s2 = np.asarray(g2), np.asarray(s2)
    assert g2.shape == (P, L) and s2.shape == (P,)
    np.testing.assert_allclose(s2, g2.sum(axis=1), atol=1e-4, rtol=0)
    sp2 = np.asarray(sp2)
    assert np.all(np.isneginf(sp2[P:])), "pad-row scores must be -inf"
    np.testing.assert_allclose(sp2[:P], s2, atol=1e-6, rtol=0)


def test_padded_ranks_matches_breed_padded():
    """The documented contract behind the island stacked epoch:
    ``padded_ranks(gp, s, compute_ranks(s, k_tie), key)`` with
    ``(_, k_tie) = split(key)`` must return exactly what
    ``breed_padded(gp, s, key)`` returns — the hoisted-sort path cannot
    drift from the all-in-one one."""
    from libpga_tpu.objectives import onemax

    P, L, K = 512, 20, 128
    with _interpret():
        breed = make_pallas_breed(
            P, L, deme_size=K, mutation_rate=0.0, elitism=2,
            fused_obj=onemax.kernel_rowwise,
        )
        gp = jax.random.uniform(jax.random.key(0), (breed.Pp, breed.Lp))
        sp = jnp.sum(gp, axis=1)
        key = jax.random.key(7)
        g_a, s_a = breed.padded(gp, sp, key)
        _, k_tie = jax.random.split(key)
        ranks = breed.compute_ranks(sp, k_tie)
        g_b, s_b = breed.padded_ranks(gp, sp, ranks, key)
    np.testing.assert_array_equal(np.asarray(g_a), np.asarray(g_b))
    np.testing.assert_array_equal(np.asarray(s_a), np.asarray(s_b))


def test_compute_ranks_stacked_matches_per_island():
    """compute_ranks on stacked (I, Pp) scores — ONE flattened (I·G, K)
    sort, the island runner's hoist — must pair each island with its own
    demes: for tie-free scores the ranks are tie-stream independent, so
    the stacked result must equal per-island calls exactly."""
    P, L, K = 384, 8, 128
    with _interpret():
        breed = make_pallas_breed(P, L, deme_size=K, mutation_rate=0.0)
        I = 4
        scores = jax.random.normal(jax.random.key(1), (I, breed.Pp))
        k = jax.random.key(2)
        stacked = breed.compute_ranks(scores, k)
        per_island = jnp.stack(
            [breed.compute_ranks(scores[i], jax.random.fold_in(k, i))
             for i in range(I)]
        )
    assert stacked.shape == per_island.shape
    np.testing.assert_array_equal(np.asarray(stacked), np.asarray(per_island))


def test_selection_strategies_in_kernel():
    """Truncation and linear-rank selection run in-kernel as alternate
    inverse CDFs over the same rank machinery: zero PRNG bits sample
    rank 0 for every strategy, so the deme-row-0 structure must hold;
    invalid params raise at build time; unknown kinds decline."""
    import pytest

    P, L, K = 512, 12, 128
    G = P // K
    genomes = (
        jnp.broadcast_to(jnp.arange(P, dtype=jnp.float32)[:, None], (P, L)) / P
    )
    expect = np.asarray([((r % G) * K) / P for r in range(P)], np.float32)
    with _interpret():
        for kind, param in (
            ("truncation", 0.25), ("truncation", None),
            ("linear_rank", 1.5), ("linear_rank", None),
        ):
            breed = make_pallas_breed(
                P, L, deme_size=K, mutation_rate=0.0,
                selection_kind=kind, selection_param=param,
            )
            assert breed is not None, (kind, param)
            out = np.asarray(
                breed(genomes, deme_rank0_scores(P, K), jax.random.key(0))
            )
            np.testing.assert_allclose(
                out, np.broadcast_to(expect[:, None], (P, L)),
                atol=2e-5, rtol=0, err_msg=str((kind, param)),
            )
    with pytest.raises(ValueError):
        make_pallas_breed(P, L, selection_kind="truncation",
                          selection_param=1.5)
    with pytest.raises(ValueError):
        make_pallas_breed(P, L, selection_kind="linear_rank",
                          selection_param=1.0)
    with pytest.raises(ValueError):
        # unknown kinds are config errors (canonical message from
        # ops/select.resolve_selection), not silent XLA fallbacks
        make_pallas_breed(P, L, selection_kind="roulette")


def test_gaussian_keeps_pad_lanes_zero():
    """Gaussian mutation fires per-gene over the whole (K, Lp) tile, so
    without the lane guard it would write noise into pad lanes (L..Lp)
    and break the pads-stay-zero invariant that ``pad_ok`` fused
    objectives (and the final [:, :L] slice's cheapness) rely on. Zero
    PRNG bits fire the gate everywhere at rate=1 — pad lanes must still
    come back exactly zero."""
    P, L, K = 256, 100, 128  # Lp=128 > L
    with _interpret():
        breed = make_pallas_breed(
            P, L, deme_size=K, mutate_kind="gaussian",
            mutation_rate=1.0, mutation_sigma=0.1,
        )
        gp = jnp.pad(jnp.full((P, L), 0.5, jnp.float32), ((0, 0), (0, 28)))
        out = np.asarray(breed.padded(gp, jnp.zeros((P,)), jax.random.key(0)))
    assert out.shape == (P, 128)
    assert np.all(out[:, L:] == 0.0), "pad lanes must stay zero"
    # and the real lanes did mutate (gate fired at rate=1)
    assert np.all(out[:, :L] != 0.5)


def test_padded_tail_nan_scores_never_select_pads():
    """Round-3 review finding: with the rank sort done outside the
    kernel, a NaN score in the tail deme sorted AFTER the pads' -inf
    (XLA places NaN above +inf once negated), handing pad rows real
    ranks < V — all-zero pad genomes could then be selected as parents.
    NaN scores must rank last among REAL rows and pads strictly after
    every real row."""
    P, L, K = 300, 12, 128
    with _interpret():
        breed = make_pallas_breed(P, L, deme_size=K, mutation_rate=0.0)
        genomes = jnp.full((P, L), 0.5, dtype=jnp.float32)
        # every real score in the tail deme (rows 256..299) is NaN
        scores = deme_rank0_scores(P, K).at[256:].set(jnp.nan)
        out = np.asarray(breed(genomes, scores, jax.random.key(0)))
    # zero PRNG bits -> every child copies its deme's rank-0 row, which
    # must be a REAL row (gene 0.5), never an all-zero pad
    np.testing.assert_array_equal(out, np.full((P, L), 0.5, np.float32))


def test_padded_population_through_island_runner():
    """Island sizes with no deme divisor run through the island epoch's
    padded path with carried scores consistent with carried genomes."""
    from libpga_tpu.objectives import onemax
    from libpga_tpu.parallel.islands import run_islands_stacked

    I, S, L, K = 2, 300, 12, 128
    with _interpret():
        breed = make_pallas_breed(
            S, L, deme_size=K, mutation_rate=0.0,
            fused_obj=onemax.kernel_rowwise,
        )
        stacked = jax.random.uniform(jax.random.key(0), (I, S, L))
        genomes, scores, gens = run_islands_stacked(
            breed, onemax, stacked, jax.random.key(1), n=4, m=2, pct=0.05
        )
    genomes, scores = np.asarray(genomes), np.asarray(scores)
    assert gens == 4
    assert genomes.shape == (I, S, L) and scores.shape == (I, S)
    np.testing.assert_allclose(scores, genomes.sum(axis=2), atol=2e-4, rtol=0)


def test_fused_evaluation_scores_match_genome_order():
    """With fused evaluation (kernel_rowwise objective) the scores output
    must be reordered to match the riffle-shuffled genome rows: with zero
    PRNG bits child r is a copy of row 0 of deme r % G, so its fused score
    must equal obj(that row) — this pins the (G,K) transpose in
    breed_padded against the genome output's k*G+i interleave.
    (_layout="riffle": the fused default is now the ping-pong layout,
    whose score ordering is pinned by tests/test_pingpong.py.)"""
    from libpga_tpu.objectives import onemax

    P, L, K = 1024, 20, 128
    G = P // K
    with _interpret():
        breed = make_pallas_breed(
            P, L, deme_size=K, mutation_rate=0.0,
            fused_obj=onemax.kernel_rowwise, _layout="riffle",
        )
        genomes = (
            jnp.broadcast_to(jnp.arange(P, dtype=jnp.float32)[:, None], (P, L))
            / P
        )
        g2, s2 = breed(genomes, deme_rank0_scores(P, K), jax.random.key(0))
    g2, s2 = np.asarray(g2), np.asarray(s2)
    assert s2.shape == (P,)
    # fused score r == onemax(genome row r) == L * (deme base)/P
    expect = np.asarray([L * ((r % G) * K) / P for r in range(P)], np.float32)
    np.testing.assert_allclose(s2, expect, atol=1e-4, rtol=0)
    np.testing.assert_allclose(g2.sum(axis=1), s2, atol=1e-4, rtol=0)


def test_fused_breed_through_island_runner():
    """run_islands_stacked must dispatch on breed.fused: a fused Pallas
    breed runs under the island runner's vmap with its in-kernel scores
    kept consistent with the carried genomes (scores == rowwise(genomes)
    after every epoch, including migration bookkeeping)."""
    from libpga_tpu.objectives import onemax
    from libpga_tpu.parallel.islands import run_islands_stacked

    I, S, L, K = 2, 512, 20, 128
    with _interpret():
        breed = make_pallas_breed(
            S, L, deme_size=K, mutation_rate=0.0,
            fused_obj=onemax.kernel_rowwise,
        )
        assert breed.fused
        stacked = jax.random.uniform(jax.random.key(0), (I, S, L))
        genomes, scores, gens = run_islands_stacked(
            breed, onemax, stacked, jax.random.key(1), n=4, m=2, pct=0.05
        )
    genomes, scores = np.asarray(genomes), np.asarray(scores)
    assert gens == 4
    assert genomes.shape == (I, S, L) and scores.shape == (I, S)
    np.testing.assert_allclose(scores, genomes.sum(axis=2), atol=2e-4, rtol=0)


def test_bf16_gene_mode_structure():
    """bf16 gene mode: single-matmul selection must still reproduce the
    deme-row-0 structure exactly (bf16 one-hot selection of bf16 genes is
    exact) and preserve the dtype."""
    P, L, K = 512, 16, 128
    G = P // K
    with _interpret():
        breed = make_pallas_breed(
            P, L, deme_size=K, mutation_rate=0.0, gene_dtype=jnp.bfloat16
        )
        genomes = (
            jnp.broadcast_to(jnp.arange(P, dtype=jnp.float32)[:, None], (P, L))
            / P
        ).astype(jnp.bfloat16)
        out = breed(genomes, deme_rank0_scores(P, K), jax.random.key(0))
    assert out.dtype == jnp.bfloat16
    out = np.asarray(out.astype(jnp.float32))
    gn = np.asarray(genomes.astype(jnp.float32))
    for r in range(0, P, 31):
        np.testing.assert_array_equal(out[r], gn[(r % G) * K])


def test_engine_bf16_genes_on_xla_path():
    """gene_dtype=bfloat16 works end-to-end on the XLA path (CPU) and the
    population keeps its dtype through runs."""
    from libpga_tpu import PGA, PGAConfig

    pga = PGA(seed=0, config=PGAConfig(gene_dtype=jnp.bfloat16))
    pop = pga.create_population(256, 8)
    pga.set_objective("onemax")
    pga.run(5)
    assert pga.population(pop).genomes.dtype == jnp.bfloat16
    assert pga.get_best(pop).shape == (8,)


def test_deme_grouping_selection_and_vmem_cap():
    """Both dtypes group demes when G divides (bf16 capped at D=4, f32
    at D=8 since the round-5 re-sweep, D=16 for const-carrying fused
    objectives); long genomes whose grouped block would blow the VMEM
    budget fall back to smaller D instead of failing at Mosaic compile
    time; explicit requests round down to a valid divisor and are
    reported via breed.D."""
    b = make_pallas_breed(4096, 16, deme_size=256, gene_dtype=jnp.bfloat16)
    assert b.D == 4  # G=16, divisible; bf16 cap
    b = make_pallas_breed(4096, 16, deme_size=256)
    assert b.D == 8  # f32 cap (round 5)
    from libpga_tpu.objectives.classic import make_nk_landscape

    nk = make_nk_landscape(16, 3, seed=0)
    b = make_pallas_breed(
        4096, 16, deme_size=256, fused_obj=nk.kernel_rowwise,
        fused_consts=nk.kernel_rowwise_consts,
    )
    assert b.D == 16  # const-carrying fused objective keeps D=16
    # AUTO deme size (no explicit deme_size): const-carrying f32 keeps
    # K=256 (NK-4M measured 31.8 vs 28.3 gens/sec); everything else
    # defaults to K=512 since the round-5 re-sweep.
    b = make_pallas_breed(
        4096, 16, fused_obj=nk.kernel_rowwise,
        fused_consts=nk.kernel_rowwise_consts,
    )
    assert b.K == 256 and b.D == 16
    from libpga_tpu.objectives import onemax

    b = make_pallas_breed(4096, 16, fused_obj=onemax.kernel_rowwise)
    assert b.K == 512 and b.D == 8
    # bf16, genome_len 2000 -> Lp=2048: K=512 would need ~23 MB of
    # scoped VMEM (fails to compile), so the deme is capped at K=256;
    # grouping stays within its block budget at D=2 (verified to compile
    # and run on hardware)
    b = make_pallas_breed(1 << 20, 2000, deme_size=512, gene_dtype=jnp.bfloat16)
    assert b.K == 256 and b.D == 2
    # genomes too long for even K=128 fall back to the XLA path
    from libpga_tpu.ops.pallas_step import _pick_deme_size

    assert _pick_deme_size(1 << 20, 256, genome_lanes=8192) is None
    # explicit request with G=12 (not divisible by 8) rounds down to 4
    b = make_pallas_breed(12 * 256, 16, deme_size=256, _demes_per_step=8)
    assert b.D == 4


def test_gaussian_kernel_rate_zero_and_sigma_zero_are_noops():
    """Gaussian in-kernel mutation: rate=0 never fires; rate=1 with
    sigma=0 fires everywhere but perturbs nothing (clip is identity on
    [0,1) genes) — both must reproduce the zero-bits breeding structure
    exactly."""
    P, L, K = 256, 8, 128
    G = P // K
    genomes = (
        jnp.broadcast_to(jnp.arange(P, dtype=jnp.float32)[:, None], (P, L)) / P
    )
    outs = {}
    with _interpret():
        for rate, sigma in ((0.0, 0.5), (1.0, 0.0)):
            breed = make_pallas_breed(
                P, L, deme_size=K, mutation_rate=rate,
                mutation_sigma=sigma, mutate_kind="gaussian",
            )
            assert breed is not None
            outs[(rate, sigma)] = np.asarray(
                breed(genomes, deme_rank0_scores(P, K), jax.random.key(0))
            )
    expect = np.asarray([((r % G) * K) / P for r in range(P)], np.float32)
    for out in outs.values():
        np.testing.assert_allclose(
            out, np.broadcast_to(expect[:, None], (P, L)), atol=2e-5, rtol=0
        )


def test_runtime_mutation_params_override_defaults():
    """mparams passed at call time must override the construction-time
    rate — the mechanism that lets annealing schedules reuse one
    compilation. Zero PRNG bits: point mutation at rate 1 sets gene 0 of
    every row to draw 0 (= 0.0); at the default rate 0 nothing fires."""
    P, L, K = 256, 8, 128
    genomes = jnp.full((P, L), 0.5, dtype=jnp.float32)
    with _interpret():
        breed = make_pallas_breed(P, L, deme_size=K, mutation_rate=0.0)
        quiet = np.asarray(breed(genomes, jnp.zeros((P,)), jax.random.key(0)))
        fired = np.asarray(
            breed(
                genomes, jnp.zeros((P,)), jax.random.key(0),
                jnp.asarray([[1.0, 0.0]], dtype=jnp.float32),
            )
        )
    np.testing.assert_array_equal(quiet, np.full((P, L), 0.5, np.float32))
    np.testing.assert_array_equal(fired[:, 0], np.zeros((P,), np.float32))
    np.testing.assert_array_equal(fired[:, 1:], np.full((P, L - 1), 0.5, np.float32))


def test_fused_elitism_preserves_top_rows():
    """Fused breed with elitism=e: rows 0..e-1 of the output must be the
    previous generation's top-e genomes with their scores — the same
    slots the XLA breed uses — while the rest follow the zero-bits
    breeding structure."""
    from libpga_tpu.objectives import onemax

    P, L, K = 256, 8, 128
    G = P // K
    genomes = (
        jnp.broadcast_to(jnp.arange(P, dtype=jnp.float32)[:, None], (P, L)) / P
    )
    # scores unrelated to genome content: rows 131 and 7 are the elite
    scores = jnp.zeros((P,), jnp.float32).at[131].set(9.0).at[7].set(5.0)
    with _interpret():
        # riffle layout pinned: the ping-pong elitism epilogue has its
        # own structural test in tests/test_pingpong.py
        breed = make_pallas_breed(
            P, L, deme_size=K, mutation_rate=0.0, elitism=2,
            fused_obj=onemax.kernel_rowwise, _layout="riffle",
        )
        assert breed is not None and breed.elitism == 2
        g2, s2 = breed(genomes, scores, jax.random.key(0))
    g2, s2 = np.asarray(g2), np.asarray(s2)
    gn = np.asarray(genomes)
    np.testing.assert_array_equal(g2[0], gn[131])
    np.testing.assert_array_equal(g2[1], gn[7])
    assert s2[0] == 9.0 and s2[1] == 5.0
    # non-elite rows keep the zero-bits structure: each child copies its
    # deme's BEST-scoring row (rank 0) — row 7 in deme 0, row 131 in
    # deme 1
    deme_best = {0: 7, 1: 131}
    for r in range(2, P, 41):
        np.testing.assert_allclose(
            g2[r], gn[deme_best[r % G]], atol=2e-5, rtol=0
        )
    np.testing.assert_allclose(s2[2:], g2[2:].sum(axis=1), atol=1e-4, rtol=0)


def test_gaussian_islands_with_params_through_runner():
    """A gaussian takes_params breed runs through run_islands_stacked
    with explicit mparams, keeping carried scores consistent."""
    from libpga_tpu.objectives import onemax
    from libpga_tpu.parallel.islands import run_islands_stacked

    I, S, L, K = 2, 256, 8, 128
    with _interpret():
        breed = make_pallas_breed(
            S, L, deme_size=K, mutate_kind="gaussian", mutation_rate=0.0,
            fused_obj=onemax.kernel_rowwise,
        )
        assert breed.takes_params
        stacked = jax.random.uniform(jax.random.key(0), (I, S, L))
        genomes, scores, gens = run_islands_stacked(
            breed, onemax, stacked, jax.random.key(1), n=4, m=2, pct=0.05,
            mparams=jnp.asarray([[0.0, 0.0]], dtype=jnp.float32),
        )
    genomes, scores = np.asarray(genomes), np.asarray(scores)
    assert gens == 4
    np.testing.assert_allclose(scores, genomes.sum(axis=2), atol=2e-4, rtol=0)


def test_island_pallas_path_custom_objective_with_elitism(monkeypatch):
    """Round-2 verdict finding: elitism + a custom (non-rowwise) objective
    silently dropped the island run to the ~5× slower XLA path. The
    Pallas breed must now be engaged (built without in-kernel elitism)
    with the elite carry applied by the island epoch — the global best
    can never regress across epochs."""
    from libpga_tpu import PGA, PGAConfig

    custom_obj = lambda g: -jnp.sum((g - 0.25) ** 2, axis=-1)

    pga = PGA(seed=0, config=PGAConfig(elitism=4))
    handles = [pga.create_population(256, 16) for _ in range(4)]
    pga.set_objective(custom_obj)
    monkeypatch.setattr(pga, "_pallas_gate", lambda: True)

    pga.evaluate_all()
    best0 = max(
        float(jnp.max(pga.population(h).scores)) for h in handles
    )
    with _interpret():
        breed = pga._pallas_island_breed(256, 16)
        assert breed is not None, "fast path must engage for non-rowwise+elitism"
        assert not breed.fused and breed.elitism == 0  # epoch carries elites
        pga.run_islands(4, 2, 0.1)
    best1 = max(
        float(jnp.max(pga.population(h).scores)) for h in handles
    )
    assert best1 >= best0 - 1e-6
    # carried scores must describe the carried genomes
    for h in handles:
        pop = pga.population(h)
        np.testing.assert_allclose(
            np.asarray(pop.scores),
            np.asarray(custom_obj(pop.genomes)),
            atol=1e-5,
        )


def test_order_crossover_kernel_structure():
    """Zero-entropy interpret mode: every tournament candidate is deme
    row 0, so both parents are that row and the kernel's order crossover
    must reproduce the XLA operator's semantics exactly: first
    occurrence of each decoded city is kept, later duplicates fall back
    to the raw random value (0.0 under zero bits). Swap mutation under
    zero bits swaps position 0 with itself — a no-op."""
    from libpga_tpu.ops.crossover import order_preserving_crossover

    P, L, K = 256, 10, 128
    G = P // K
    rng = np.random.default_rng(3)
    genomes = np.asarray(
        (rng.permuted(np.tile(np.arange(L), (P, 1)), axis=1) + 0.5) / L,
        dtype=np.float32,
    )
    # Plant duplicates in each deme's row 0 so the rand-fallback path is
    # exercised: positions 3 and 7 decode to the same city as 0 and 1.
    for d in range(G):
        genomes[d * K, 3] = genomes[d * K, 0]
        genomes[d * K, 7] = genomes[d * K, 1]

    with _interpret():
        breed = make_pallas_breed(
            P, L, deme_size=K, crossover_kind="order", mutate_kind="swap",
            mutation_rate=0.9,
        )
        assert breed is not None and breed.crossover_kind == "order"
        out = np.asarray(
            breed(
                jnp.asarray(genomes), deme_rank0_scores(P, K),
                jax.random.key(0),
            )
        )

    for d in range(G):
        row0 = jnp.asarray(genomes[d * K])
        expect = np.asarray(
            order_preserving_crossover(row0, row0, jnp.zeros((L,)))
        )
        # children of deme d land at output rows r*G + d (riffle layout)
        np.testing.assert_allclose(
            out[np.arange(K) * G + d], np.tile(expect, (K, 1)), atol=2e-5
        )


def test_order_crossover_gating():
    """Order crossover serves f32 only (bf16 decode resolution corrupts
    cities) and maps from the engine's operator registry."""
    from libpga_tpu import PGA
    from libpga_tpu.ops.crossover import order_preserving_crossover
    from libpga_tpu.ops.mutate import make_swap_mutate

    assert make_pallas_breed(
        1024, 10, crossover_kind="order", gene_dtype=jnp.bfloat16
    ) is None
    assert make_pallas_breed(1024, 10, crossover_kind="nope") is None

    pga = PGA(seed=0)
    pga.set_crossover(order_preserving_crossover)
    pga.set_mutate(make_swap_mutate(0.3))
    assert pga._crossover_kind() == "order"
    assert pga._mutate_kind() == "swap"
    assert float(np.asarray(pga._mutate_params())[0, 0]) == np.float32(0.3)


def test_mutation_rate_zero_never_fires():
    """rate=0 must be a strict no-op even for zero random bits (the gate
    is strict '<'; the reference's '<=' would fire on u == 0)."""
    P, L, K = 256, 8, 128
    with _interpret():
        breed = make_pallas_breed(P, L, deme_size=K, mutation_rate=0.0)
        genomes = jnp.full((P, L), 0.5, dtype=jnp.float32)
        out = np.asarray(breed(genomes, jnp.zeros((P,)), jax.random.key(0)))
    np.testing.assert_array_equal(out, np.full((P, L), 0.5, dtype=np.float32))


# ----------------------------------------------------------- multigen kernel


def _sum_obj():
    """The onemax fused rowwise form + consts, as the engine resolves it."""
    from libpga_tpu.objectives import get as get_obj

    obj = get_obj("onemax")
    return obj.kernel_rowwise, tuple(getattr(obj, "kernel_rowwise_consts", ()))


def test_multigen_requires_fused_objective():
    from libpga_tpu.ops.pallas_step import make_pallas_multigen

    assert make_pallas_multigen(512, 16, fused_obj=None) is None


def test_multigen_zero_steps_is_a_riffle_permutation():
    """steps=0 must pass the population through untouched (up to the
    riffle reshuffle of the output layout): the genome ROW multiset and
    the aligned scores are preserved exactly."""
    from libpga_tpu.ops.pallas_step import make_pallas_multigen

    P, L = 512, 20
    with _interpret():
        fused, consts = _sum_obj()
        bm = make_pallas_multigen(
            P, L, deme_size=128, fused_obj=fused, fused_consts=consts
        )
        g = jax.random.uniform(jax.random.key(1), (P, L), dtype=jnp.float32)
        s = jnp.sum(g, axis=1)
        g0, s0 = bm(g, s, jax.random.key(0), 0)
    # scores stay aligned with their genomes...
    np.testing.assert_allclose(
        np.asarray(s0), np.asarray(jnp.sum(g0, axis=1)), rtol=1e-5
    )
    # ...and the population is the same multiset of rows
    order_in = np.lexsort(np.asarray(g).T)
    order_out = np.lexsort(np.asarray(g0).T)
    np.testing.assert_array_equal(
        np.asarray(g)[order_in], np.asarray(g0)[order_out]
    )


def test_multigen_runtime_step_count_and_consistency():
    """The SAME compiled kernel serves different runtime step counts,
    and returned scores always equal the objective of the returned
    genomes (evaluation happens in-kernel every sub-generation)."""
    from libpga_tpu.ops.pallas_step import make_pallas_multigen

    P, L = 512, 20
    with _interpret():
        fused, consts = _sum_obj()
        bm = make_pallas_multigen(
            P, L, deme_size=128, fused_obj=fused, fused_consts=consts
        )
        g = jax.random.uniform(jax.random.key(1), (P, L), dtype=jnp.float32)
        s = jnp.sum(g, axis=1)
        stepped = jax.jit(lambda t: bm(g, s, jax.random.key(0), t))
        for t in (1, 3):
            gt, st = stepped(jnp.int32(t))
            np.testing.assert_allclose(
                np.asarray(st), np.asarray(jnp.sum(gt, axis=1)), rtol=1e-5
            )


def test_multigen_structure_matches_single_gen():
    """Zero PRNG bits + rank-0 scores: after any number of sub-gens the
    whole deme collapses onto copies of its original row 0 (every child
    descends from rank 0 and the fused score follows) — the same
    structural expectation the one-generation kernel satisfies.
    (_layout="riffle": the ping-pong multigen structure is pinned in
    tests/test_pingpong.py.)"""
    from libpga_tpu.ops.pallas_step import make_pallas_multigen

    P, L, K = 512, 12, 128
    with _interpret():
        fused, consts = _sum_obj()
        bm = make_pallas_multigen(
            P, L, deme_size=K, mutation_rate=0.0,
            fused_obj=fused, fused_consts=consts, _layout="riffle",
        )
        genomes = (
            jnp.broadcast_to(jnp.arange(P, dtype=jnp.float32)[:, None], (P, L))
            / P
        )
        # zero tie-break bits -> ties broken by lane index, so use
        # strictly-decreasing in-deme scores to pin rank 0 at deme row 0
        scores = deme_rank0_scores(P, K)
        g2, s2 = bm(genomes, scores, jax.random.key(0), 2)
    G = P // K
    expect = np.asarray([((r % G) * K) / P for r in range(P)], np.float32)
    np.testing.assert_allclose(np.asarray(g2[:, 0]), expect, rtol=1e-4)
    np.testing.assert_allclose(
        np.asarray(s2), np.asarray(jnp.sum(g2, axis=1)), rtol=1e-4
    )


def test_multigen_target_freeze_preserves_achiever():
    """A launch whose group already satisfies the target must return the
    population unchanged (modulo the riffle permutation) for ANY step
    count — the in-kernel freeze."""
    from libpga_tpu.ops.pallas_step import make_pallas_multigen

    P, L = 512, 20
    with _interpret():
        fused, consts = _sum_obj()
        bm = make_pallas_multigen(
            P, L, deme_size=128, fused_obj=fused, fused_consts=consts
        )
        g = jax.random.uniform(jax.random.key(1), (P, L), dtype=jnp.float32)
        s = jnp.sum(g, axis=1)
        gf, sf = bm(g, s, jax.random.key(0), 5, None, float(jnp.max(s)) - 0.5)
    np.testing.assert_array_equal(
        np.sort(np.asarray(sf)), np.sort(np.asarray(s))
    )


def test_multigen_per_deme_elitism_preserves_global_top():
    """elitism=e per deme preserves the global top-e across
    sub-generations: each global top-j row (j <= e) is within the top-e
    of its own deme."""
    from libpga_tpu.ops.pallas_step import make_pallas_multigen

    P, L, e = 512, 20, 2
    with _interpret():
        fused, consts = _sum_obj()
        bm = make_pallas_multigen(
            P, L, deme_size=128, elitism=e,
            fused_obj=fused, fused_consts=consts,
        )
        g = jax.random.uniform(jax.random.key(1), (P, L), dtype=jnp.float32)
        s = jnp.sum(g, axis=1)
        g2, s2 = bm(g, s, jax.random.key(0), 3)
    top_in = np.sort(np.asarray(s))[-e:]
    top_out = np.sort(np.asarray(s2))[-e:]
    assert np.all(top_out >= top_in - 1e-4), (top_in, top_out)


def test_multigen_padded_population():
    """A population with no exact deme divisor pads internally; returned
    rows are all real children with consistent scores."""
    from libpga_tpu.ops.pallas_step import make_pallas_multigen

    P, L = 300, 33
    with _interpret():
        fused, consts = _sum_obj()
        bm = make_pallas_multigen(
            P, L, deme_size=128, fused_obj=fused, fused_consts=consts
        )
        assert bm.Pp == 384
        g = jax.random.uniform(jax.random.key(2), (P, L), dtype=jnp.float32)
        s = jnp.sum(g, axis=1)
        g2, s2 = bm(g, s, jax.random.key(0), 3)
    assert g2.shape == (P, L) and s2.shape == (P,)
    assert np.all(np.isfinite(np.asarray(s2)))
    np.testing.assert_allclose(
        np.asarray(s2), np.asarray(jnp.sum(g2, axis=1)), rtol=1e-4
    )
    assert float(jnp.mean(s2)) > float(jnp.mean(s))


def test_multigen_run_loop_exact_generation_count():
    """The chunked run loop lands exactly on n via the runtime remainder
    (n % T != 0), and the fallback contract (genomes, scores, gens)
    holds."""
    from libpga_tpu.ops.pallas_step import make_pallas_run
    from libpga_tpu.objectives import get as get_obj

    obj = get_obj("onemax")
    P, L = 512, 20
    with _interpret():
        factory = make_pallas_run(obj, generations_per_launch=3)
        # make_pallas_run requires the TPU backend for the real kernel;
        # under interpret mode on CPU it declines. Exercise the loop
        # construction directly instead.
        from libpga_tpu.ops.pallas_step import (
            make_pallas_multigen, _multigen_run_loop,
        )

        bm = make_pallas_multigen(
            P, L, deme_size=128, fused_obj=obj.kernel_rowwise,
            fused_consts=tuple(getattr(obj, "kernel_rowwise_consts", ())),
        )
        run = _multigen_run_loop(obj, bm, P, L, 3, donate=False)
        g = jax.random.uniform(jax.random.key(1), (P, L), dtype=jnp.float32)
        g2, s2, gens = run(
            g, jax.random.key(0), jnp.int32(10), jnp.float32(jnp.inf),
            bm.default_params,
        )
    assert int(gens) == 10
    np.testing.assert_allclose(
        np.asarray(s2), np.asarray(jnp.sum(g2, axis=1)), rtol=1e-4
    )


def test_order_crossover_long_genome_lowers_and_repairs():
    """The runtime-loop order walk serves genome_len > 256 (the old
    trace-time unroll declined it): permutation parents breed
    permutation children at L=300, and the factory no longer returns
    None."""
    from libpga_tpu.ops.pallas_step import make_pallas_breed

    P, L = 256, 300
    with _interpret():
        breed = make_pallas_breed(
            P, L, deme_size=128, crossover_kind="order",
            mutate_kind="swap", mutation_rate=0.0,
        )
        assert breed is not None
        rng = np.random.default_rng(0)
        perms = (
            rng.permuted(np.tile(np.arange(L), (P, 1)), axis=1) + 0.5
        ).astype(np.float32) / L
        out = np.asarray(
            breed(
                jnp.asarray(perms),
                jnp.asarray(rng.random(P), dtype=jnp.float32),
                jax.random.key(0),
            )
        )
    cities = np.clip(np.floor(out * L), 0, L - 1).astype(int)
    uniq = np.array([len(np.unique(r)) for r in cities])
    assert uniq.min() == L, uniq.min()


def test_tsp_coords_matches_per_genome_form():
    """make_tsp_coords: the batched one-hot-gather form must agree with
    the per-genome indexed form, duplicates penalized identically."""
    from libpga_tpu.objectives import make_tsp_coords, random_tsp_coords

    L = 40
    xy = random_tsp_coords(L, seed=1)
    obj = make_tsp_coords(xy)
    rng = np.random.default_rng(2)
    g = rng.random((16, L)).astype(np.float32)  # duplicates near-certain
    rows = np.asarray(obj.rows(jnp.asarray(g)))
    per = np.asarray([float(obj(jnp.asarray(r))) for r in g])
    np.testing.assert_allclose(rows, per, rtol=1e-4, atol=1e-2)


def test_order_crossover_long_genome_visited_semantics():
    """Deterministic walk check through the DYNAMIC loop body (L=300 >=
    2*U, so the static tail alone can't mask a bug): zero PRNG bits make
    every child the dedup-walk of its deme's rank-0 row — the first
    occurrence of each city keeps its raw gene, every later duplicate
    falls through take1 AND take2 (same city) to the zero random
    fallback. Exercises the bitmask membership test, the mark update,
    and the fallback write at every dynamic step."""
    from libpga_tpu.ops.pallas_step import make_pallas_breed

    P, L, K = 256, 300, 128
    # rank-0 rows carry a known duplicate pattern: city l % 150 at
    # position l (positions 150.. are all duplicates)
    pattern = ((np.arange(L) % 150) + 0.5).astype(np.float32) / L
    rng = np.random.default_rng(1)
    g = rng.random((P, L)).astype(np.float32)
    g[0] = pattern  # deme 0 rank-0 row
    g[K] = pattern  # deme 1 rank-0 row
    with _interpret():
        breed = make_pallas_breed(
            P, L, deme_size=K, crossover_kind="order",
            mutate_kind="swap", mutation_rate=0.0,
        )
        out = np.asarray(
            breed(
                jnp.asarray(g), deme_rank0_scores(P, K), jax.random.key(0)
            )
        )
    expect = pattern.copy()
    expect[150:] = 0.0  # duplicates -> zero fallback
    # atol: parent genes round-trip the hi/lo bf16 selection matmul
    # (~1e-5 documented accuracy); fallback zeros must be exact.
    np.testing.assert_allclose(out, np.tile(expect, (P, 1)), atol=2e-5)
    np.testing.assert_array_equal(out[:, 150:], 0.0)


class TestFusedTspEval:
    """Gene-major in-kernel TSP scoring (``_tsp_eval_gene_major``) —
    the long-genome evaluation path (round-4 verdict item 3): fused
    scores must equal the objective's XLA ``rows`` oracle, the factory
    must gate on order crossover, and the "genes" duplicate mode must
    agree between the per-genome and batched forms."""

    def _tsp(self, C, seed=2):
        from libpga_tpu.objectives.classic import (
            make_tsp_coords, random_tsp_coords,
        )

        coords = random_tsp_coords(C, seed=seed)
        return make_tsp_coords(coords, duplicate_mode="genes")

    @pytest.mark.parametrize("C", [20, 37])  # 37: tail batch + A > 1
    def test_fused_scores_match_oracle(self, C):
        from libpga_tpu.ops.pallas_step import make_pallas_breed

        tsp = self._tsp(C)
        P = 256
        rng = np.random.default_rng(0)
        perms = np.stack([rng.permutation(C) for _ in range(P)])
        g = jnp.asarray(((perms + 0.5) / C).astype(np.float32))
        s = tsp.rows(g)
        with _interpret():
            breed = make_pallas_breed(
                P, C, deme_size=128, crossover_kind="order",
                mutate_kind="swap", fused_tsp=tsp.kernel_gene_major,
            )
            assert breed is not None and breed.fused
            g2, s2 = breed(g, s, jax.random.key(1))
        oracle = np.asarray(tsp.rows(jnp.asarray(g2)))
        np.testing.assert_allclose(
            np.asarray(s2), oracle, rtol=1e-4, atol=0.5
        )

    def test_duplicate_genes_mode_counts_and_scores(self):
        """genes mode: dups = L − distinct; per-genome and rows forms
        agree, including on genomes WITH duplicates; valid permutations
        score identically to pairs mode."""
        from libpga_tpu.objectives.classic import (
            make_tsp_coords, random_tsp_coords,
        )

        C = 16
        coords = random_tsp_coords(C, seed=3)
        genes = make_tsp_coords(coords, duplicate_mode="genes")
        pairs = make_tsp_coords(coords, duplicate_mode="pairs")
        rng = np.random.default_rng(1)
        perm = ((rng.permutation(C) + 0.5) / C).astype(np.float32)
        g = jnp.asarray(perm)
        assert np.isclose(float(genes(g)), float(pairs(g)), rtol=1e-5)
        # introduce a triple: 2 duplicate GENES, 6 ordered pairs
        gd = g.at[3].set(g[5]).at[7].set(g[5])
        d_genes = float(genes(gd))
        d_pairs = float(pairs(gd))
        assert np.isclose(
            float(genes.rows(gd[None, :])[0]), d_genes, rtol=1e-5
        )
        # the penalty difference between modes is (6-2) * penalty
        assert np.isclose(d_pairs - d_genes, -4 * 10_000.0, rtol=1e-3)

    def test_factory_gates(self):
        from libpga_tpu.ops.pallas_step import make_pallas_breed

        tsp = self._tsp(20)
        # uniform crossover: the gene-major evaluator declines (no
        # order scratch) -> plain unfused breed
        breed = make_pallas_breed(
            256, 20, deme_size=128, crossover_kind="uniform",
            mutate_kind="point", fused_tsp=tsp.kernel_gene_major,
        )
        assert breed is not None and not breed.fused
        # pairs mode carries no kernel hook at all
        from libpga_tpu.objectives.classic import (
            make_tsp_coords, random_tsp_coords,
        )

        assert not hasattr(
            make_tsp_coords(random_tsp_coords(20), duplicate_mode="pairs"),
            "kernel_gene_major",
        )
