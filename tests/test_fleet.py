"""Cross-process serving fleet (ISSUE 8): spool protocol, leases,
kill/drain recovery, quarantine, backpressure.

The two acceptance properties are bit-identity under violence:

- a worker killed with SIGKILL mid-batch has its lease recovered and
  its batch re-run on a survivor, landing bit-identical to an
  uninterrupted same-seed single-process run (seeds travel with the
  ticket, never the worker);
- a SIGTERM drain checkpoints in-flight supervised runs at a chunk
  boundary and a restarted fleet resumes them, finishing bit-identical
  to an uninterrupted same-seed supervised run at the same cadence.

Process-spawning tests keep shapes tiny (the whole file must fit the
tier-1 budget); the 8-process matrix lives in ``tools/fleet_smoke.py``
(CI stage 9).
"""

import json
import os
import signal
import time

import numpy as np
import pytest

from libpga_tpu import PGA, PGAConfig
from libpga_tpu.config import FleetConfig
from libpga_tpu.robustness.supervisor import supervised_run
from libpga_tpu.serving import QueueFull
from libpga_tpu.serving.fleet import (
    Fleet,
    FleetDeadLetter,
    FleetTicket,
    Spool,
    config_from_json,
    config_to_json,
)
from libpga_tpu.utils import telemetry

POP, LEN = 128, 16
CFG = PGAConfig(use_pallas=False)


def engine_run(seed, n, pop=POP, length=LEN):
    pga = PGA(seed=seed, config=CFG)
    pga.create_population(pop, length)
    pga.set_objective("onemax")
    pga.run(n)
    return np.array(pga._populations[0].genomes, copy=True)


def wait_for(cond, timeout=60, interval=0.02, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


# ------------------------------------------------------------ no-process


def test_fleet_config_validation():
    with pytest.raises(ValueError):
        FleetConfig(n_workers=0)
    with pytest.raises(ValueError):
        FleetConfig(heartbeat_s=2.0, lease_timeout_s=3.0)  # > half
    with pytest.raises(ValueError):
        FleetConfig(max_worker_deaths=0)
    with pytest.raises(ValueError):
        FleetConfig(overflow="shed")
    with pytest.raises(ValueError):
        FleetConfig(max_pending=0)


def test_ticket_validation():
    with pytest.raises(ValueError):
        FleetTicket(size=0, genome_len=8, n=1, seed=0)
    with pytest.raises(ValueError):
        FleetTicket(size=8, genome_len=8, n=-1, seed=0)
    with pytest.raises(ValueError):
        FleetTicket(size=8, genome_len=8, n=1, seed=0, checkpoint_every=-1)


def test_config_json_roundtrip():
    import jax.numpy as jnp

    from libpga_tpu.utils.telemetry import TelemetryConfig

    cfg = PGAConfig(
        use_pallas=False, elitism=2, selection="truncation",
        selection_param=0.25, mutation_rate=0.05,
        gene_dtype=jnp.bfloat16,
        telemetry=TelemetryConfig(history_gens=64),
    )
    back = config_from_json(json.loads(json.dumps(config_to_json(cfg))))
    assert back.elitism == 2
    assert back.selection == "truncation"
    assert back.selection_param == 0.25
    assert np.dtype(back.gene_dtype).name == "bfloat16"
    assert back.telemetry.history_gens == 64
    # Signature-relevant fields survive exactly: the worker's executor
    # must land in the same bucket the coordinator described.
    assert (
        back.serving_signature_fields()
        == cfg.serving_signature_fields()
    )


def test_fleet_requires_named_objective(tmp_path):
    with pytest.raises(ValueError, match="NAMED objective"):
        Fleet(str(tmp_path), lambda g: g.sum())
    with pytest.raises(KeyError):
        Fleet(str(tmp_path), "no_such_objective")


def test_batch_formation_and_spool_format(tmp_path):
    fleet = Fleet(
        str(tmp_path), "onemax", config=CFG,
        fleet=FleetConfig(n_workers=1, max_batch=2, max_wait_ms=10_000),
    )
    h1 = fleet.submit(FleetTicket(size=POP, genome_len=LEN, n=3, seed=1))
    assert fleet.spool.pending_batches() == []  # below max_batch
    h2 = fleet.submit(FleetTicket(size=POP, genome_len=LEN, n=3, seed=2))
    names = fleet.spool.pending_batches()
    assert len(names) == 1  # max_batch reached -> formed inline
    batch = Spool.read_json(fleet.spool.path("pending", names[0]))
    assert batch["spec"]["objective"] == "onemax"
    assert batch["attempts"] == []
    assert [t["tid"] for t in batch["tickets"]] == [h1.tid, h2.tid]
    assert batch["tickets"][0]["seed"] == 1
    # distinct shapes bucket separately
    fleet.submit(FleetTicket(size=POP, genome_len=2 * LEN, n=3, seed=3))
    assert fleet.flush() == 1
    assert len(fleet.spool.pending_batches()) == 2
    # supervised tickets never co-batch with plain ones
    fleet.submit(FleetTicket(size=POP, genome_len=LEN, n=3, seed=4))
    fleet.submit(
        FleetTicket(size=POP, genome_len=LEN, n=3, seed=5,
                    checkpoint_every=1)
    )
    assert fleet.flush() == 2
    fleet.close()


def test_backpressure_raise_and_block(tmp_path):
    fleet = Fleet(
        str(tmp_path), "onemax", config=CFG,
        fleet=FleetConfig(
            n_workers=1, max_pending=2, overflow="raise",
            max_wait_ms=10_000,
        ),
    )
    fleet.submit(FleetTicket(size=POP, genome_len=LEN, n=1, seed=1))
    fleet.submit(FleetTicket(size=POP, genome_len=LEN, n=1, seed=2))
    with pytest.raises(QueueFull):
        fleet.submit(FleetTicket(size=POP, genome_len=LEN, n=1, seed=3))
    fleet.close()
    with pytest.raises(RuntimeError, match="closed"):
        fleet.submit(FleetTicket(size=POP, genome_len=LEN, n=1, seed=4))


def test_publish_first_writer_wins(tmp_path):
    spool = Spool(str(tmp_path))
    a = spool.path("results", "a.tmp")
    b = spool.path("results", "b.tmp")
    final = spool.path("results", "t1.json")
    for p, content in ((a, "first"), (b, "second")):
        with open(p, "w") as fh:
            fh.write(content)
    assert spool.publish(a, final) is True
    assert spool.publish(b, final) is False  # loser discarded
    assert open(final).read() == "first"
    assert not os.path.exists(a) and not os.path.exists(b)


# -------------------------------------------------------- with processes


def test_fleet_kill9_midbatch_bit_identity(tmp_path):
    """ACCEPTANCE: SIGKILL of a worker mid-batch — the lease is
    recovered, the batch re-runs on the survivor, and every result is
    bit-identical to an uninterrupted same-seed single-process run."""
    events_path = str(tmp_path / "events.jsonl")
    log = telemetry.EventLog(events_path)
    fleet = Fleet(
        str(tmp_path / "spool"), "onemax", config=CFG,
        fleet=FleetConfig(
            n_workers=2, max_batch=2, max_wait_ms=5,
            lease_timeout_s=4.0, heartbeat_s=0.2, poll_s=0.05,
        ),
        events=log,
    )
    try:
        # Worker 0 SIGKILLs ITSELF at the start of its first batch
        # execution — a real kill -9 mid-batch, deterministically.
        fleet.start(
            worker_env={0: {"PGA_WORKER_CHAOS": "sigkill@execute:1"}}
        )
        seeds = (1, 2, 3, 4)
        handles = [
            fleet.submit(
                FleetTicket(size=POP, genome_len=LEN, n=4, seed=s)
            )
            for s in seeds
        ]
        results = [h.result(timeout=180) for h in handles]
        for seed, res in zip(seeds, results):
            assert res.generations == 4
            assert np.array_equal(res.genomes, engine_run(seed, 4)), (
                f"seed {seed} diverged after worker kill"
            )
        assert fleet.worker_deaths == 1
        assert fleet.requeues >= 1
        # ISSUE 9: the requeued tickets' traces show BOTH attempts —
        # the dead worker's claim, the coordinator's requeue, and the
        # survivor's claim — and every completed ticket's span
        # breakdown tiles >= 95% of its end-to-end time.
        both_attempts = 0
        for h in handles:
            lat = h.latency()
            spans = [lat[f"{k}_ms"] for k in
                     ("intake", "spool_wait", "execute", "publish",
                      "readback")]
            assert all(v is not None for v in spans), lat
            assert sum(spans) >= 0.95 * lat["e2e_ms"], lat
            span_kinds = [r["span"] for r in h.trace()]
            if span_kinds.count("claim") >= 2 and "requeue" in span_kinds:
                both_attempts += 1
        assert both_attempts >= 1
    finally:
        fleet.close()
        log.close()
    records = telemetry.validate_log(events_path)  # schema-valid
    kinds = [r["event"] for r in records]
    assert "worker_spawn" in kinds
    assert "worker_death" in kinds
    assert "lease_requeue" in kinds
    assert "fleet_ticket_done" in kinds


def test_fleet_drain_resume_bit_identity(tmp_path):
    """ACCEPTANCE: SIGTERM drain mid-supervised-run checkpoints at a
    chunk boundary; a restarted fleet resumes and finishes bit-identical
    to an uninterrupted same-seed supervised run at the same cadence.

    Shape note: this test must OBSERVE a mid-run sidecar from outside
    the worker. At the file's default 128x16 shape a warm chunk runs in
    low single-digit milliseconds and all N/K sidecar states can land
    between two polls (seen flaking under scheduler contention) — so
    this test uses a larger population, K=1 (a sidecar write per
    generation), and a tight poll interval."""
    N, K, SUP_POP = 24, 1, 2048
    fleet = Fleet(
        str(tmp_path / "spool"), "onemax", config=CFG,
        fleet=FleetConfig(
            n_workers=1, max_batch=1, max_wait_ms=0,
            lease_timeout_s=5.0, heartbeat_s=0.2, poll_s=0.05,
        ),
    )
    try:
        fleet.start()
        h = fleet.submit(FleetTicket(
            size=SUP_POP, genome_len=LEN, n=N, seed=9, checkpoint_every=K,
        ))
        fleet.flush()
        sidecar = fleet.spool.ckpt_path(h.tid) + ".meta.json"

        def mid_run():
            try:
                with open(sidecar) as fh:
                    return 0 < json.load(fh)["generations"] < N
            except (OSError, json.JSONDecodeError, KeyError):
                return False

        wait_for(mid_run, timeout=120, interval=0.002,
                 what="first durable checkpoint")
        assert fleet.drain() == 1
        # the unfinished ticket went back to the pending spool
        assert len(fleet.spool.pending_batches()) == 1
        assert fleet.workers_alive() == []
        fleet.start()  # fresh worker resumes from the checkpoint
        res = h.result(timeout=180)
    finally:
        fleet.close()
    ref = PGA(seed=9, config=CFG)
    ref.create_population(SUP_POP, LEN)
    ref.set_objective("onemax")
    report = supervised_run(
        ref, N, checkpoint_path=str(tmp_path / "ref.npz"),
        checkpoint_every=K,
    )
    assert res.generations == N
    assert np.array_equal(
        res.genomes, np.array(ref._populations[0].genomes)
    )
    assert res.best_score == report.best_score


def test_fleet_quarantine_after_k_worker_deaths(tmp_path):
    """A batch that kills max_worker_deaths DISTINCT workers is
    quarantined into dead/ with a flight-recorder dump (worker id + pid
    in the trailer), and its ticket fails with FleetDeadLetter instead
    of being retried forever."""
    fleet = Fleet(
        str(tmp_path / "spool"), "onemax", config=CFG,
        fleet=FleetConfig(
            n_workers=2, max_batch=1, max_wait_ms=0,
            lease_timeout_s=4.0, heartbeat_s=0.2, poll_s=0.05,
            max_worker_deaths=2,
        ),
    )
    try:
        # BOTH workers die on their first execution: two distinct
        # workers lose their lease on the same batch -> quarantine.
        chaos = {"PGA_WORKER_CHAOS": "sigkill@execute:1"}
        fleet.start(worker_env={0: chaos, 1: chaos})
        h = fleet.submit(
            FleetTicket(size=POP, genome_len=LEN, n=4, seed=7)
        )
        fleet.flush()
        with pytest.raises(FleetDeadLetter, match="2 distinct workers"):
            h.result(timeout=180)
        assert len(fleet.quarantined) == 1
        dead = fleet.spool.path("dead", fleet.quarantined[0])
        assert os.path.exists(dead)
        batch = Spool.read_json(dead)
        assert len(set(batch["attempts"])) == 2
        dump_path = dead + ".flight.jsonl"
        records = telemetry.validate_log(dump_path)  # schema-valid
        trailer = records[-1]
        assert trailer["event"] == "flight_dump"
        assert trailer["reason"] == "fleet_dead_letter"
        assert trailer["pid"] == os.getpid()  # coordinator attribution
        # ISSUE 9: the dump embeds the dead batch's span log (both
        # killed workers' claims), and the dead batch file carries the
        # same records under "trace_log" — the post-mortem trace.
        claims = [
            r for r in records
            if r["event"] == "trace_span" and r["span"] == "claim"
        ]
        assert len(claims) >= 2
        assert len({c["worker"] for c in claims}) == 2
        assert len(batch.get("trace_log", [])) >= 2
    finally:
        fleet.close()


def test_worker_heartbeat_fault_expires_lease(tmp_path):
    """Injected worker.heartbeat fault: the heartbeat thread dies while
    the worker keeps computing — the lease expires, the batch re-runs
    on a fresh worker, results stay bit-identical (first-writer-wins
    publication makes the late duplicate benign)."""
    fleet = Fleet(
        str(tmp_path / "spool"), "onemax", config=CFG,
        fleet=FleetConfig(
            n_workers=1, max_batch=1, max_wait_ms=0,
            lease_timeout_s=1.0, heartbeat_s=0.1, poll_s=0.05,
        ),
    )
    try:
        fleet.start(worker_env={0: {
            # Kill the heartbeat thread on its first tick, and slow the
            # worker's batch down via a supervised cadence so the lease
            # demonstrably expires under a live worker.
            "PGA_FAULT_SPEC":
                '{"site": "worker.heartbeat", "at_call_n": 1}',
        }})
        h = fleet.submit(FleetTicket(
            size=POP, genome_len=LEN, n=10, seed=3, checkpoint_every=1,
        ))
        fleet.flush()
        wait_for(
            lambda: fleet.requeues >= 1, timeout=120,
            what="lease expiry under a live worker",
        )
        fleet.start()  # survivor picks the requeued batch up
        res = h.result(timeout=180)
        assert res.generations == 10
    finally:
        fleet.close()
    ref = PGA(seed=3, config=CFG)
    ref.create_population(POP, LEN)
    ref.set_objective("onemax")
    supervised_run(
        ref, 10, checkpoint_path=str(tmp_path / "ref.npz"),
        checkpoint_every=1,
    )
    assert np.array_equal(
        res.genomes, np.array(ref._populations[0].genomes)
    )
