"""Coordinator HA (ISSUE 20): leader election, epoch fencing, and the
durable intake journal.

The acceptance properties, scaled down to tier-1 budgets:

- exactly one of N racing candidates wins the leader lease, and the
  epoch only ever goes up — including across a stale-lease seizure;
- a zombie leader (alive but not heartbeating past the lease timeout)
  is fenced: its late batch writes carry a stale epoch and workers
  refuse to serve them;
- replaying the intake journal is idempotent — every ticket is
  admitted exactly once no matter how many times a (new) leader
  replays — and a failover finishes the journaled work bit-identical
  to an uninterrupted single-process run.

The multi-process murder matrix (kill -9 at the four protocol points)
lives in ``tools/ha_smoke.py``.
"""

import os
import threading
import time

import numpy as np
import pytest

from libpga_tpu import PGA, PGAConfig
from libpga_tpu.config import FleetConfig
from libpga_tpu.serving import ha
from libpga_tpu.serving.fleet import (
    Fleet,
    FleetTicket,
    Spool,
    _parse_coord_chaos,
    fleet_status,
)
from libpga_tpu.serving.worker import WorkerHarness
from libpga_tpu.utils import telemetry

POP, LEN = 64, 16
CFG = PGAConfig(use_pallas=False)


def engine_run(seed, n, pop=POP, length=LEN):
    pga = PGA(seed=seed, config=CFG)
    pga.create_population(pop, length)
    pga.set_objective("onemax")
    pga.run(n)
    return np.array(pga._populations[0].genomes, copy=True)


def wait_for(cond, timeout=60, interval=0.02, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


def ha_fc(**kw):
    base = dict(
        n_workers=1, max_batch=2, max_wait_ms=5.0, lease_timeout_s=1.2,
        heartbeat_s=0.2, poll_s=0.05, metrics_flush_s=0.5, ring=False,
        coordinators=2,
    )
    base.update(kw)
    return FleetConfig(**base)


def halt(fleet):
    """Freeze a coordinator in place — the SIGSTOP/SIGKILL analog for
    in-process fleets: the monitor (heartbeats, elections, scans)
    stops, but the object and its spool state stay inspectable."""
    fleet._stop_monitor.set()
    fleet._wake.set()
    if fleet._monitor is not None:
        fleet._monitor.join(timeout=10)
    fleet._closed = True


def age_lease(spool, by_s):
    """Backdate the leader lease so the next election attempt sees it
    stale — the SIGSTOP zombie without the wall-clock wait."""
    path = spool.path(ha.COORD_DIR, ha.LEASE_NAME)
    past = time.time() - by_s
    os.utime(path, (past, past))


# ------------------------------------------------------------- election


def test_election_single_winner_race(tmp_path):
    spool = Spool(str(tmp_path / "spool"))
    wins = []
    barrier = threading.Barrier(6)

    def race(i):
        lease = ha.LeaderLease(spool, owner=f"cand-{i:06d}", timeout_s=5.0)
        barrier.wait()
        won = lease.try_acquire()
        if won is not None:
            wins.append((i, won))

    threads = [threading.Thread(target=race, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(wins) == 1, f"exactly one winner expected, got {wins}"
    _, won = wins[0]
    assert won["epoch"] == 1 and not won["seized"]
    rec = spool.read_json(spool.path(ha.COORD_DIR, ha.FENCE_NAME))
    assert rec["epoch"] == 1


def test_epoch_monotonic_across_seizure(tmp_path):
    spool = Spool(str(tmp_path / "spool"))
    a = ha.LeaderLease(spool, owner="aaaaaa", timeout_s=1.0)
    won = a.try_acquire()
    assert won == {"epoch": 1, "seized": False}
    b = ha.LeaderLease(spool, owner="bbbbbb", timeout_s=1.0)
    assert b.try_acquire() is None, "fresh lease must not be seized"
    age_lease(spool, by_s=5.0)
    won_b = b.try_acquire()
    assert won_b is not None and won_b["seized"]
    assert won_b["epoch"] == 2, "epoch must go UP across a seizure"
    assert b.fence() == 2
    # the deposed owner's heartbeat notices the loss
    assert a.heartbeat() is False
    # and a third seizure keeps climbing
    age_lease(spool, by_s=5.0)
    c = ha.LeaderLease(spool, owner="cccccc", timeout_s=1.0)
    assert c.try_acquire()["epoch"] == 3


def test_heartbeat_keeps_lease_fresh(tmp_path):
    spool = Spool(str(tmp_path / "spool"))
    a = ha.LeaderLease(spool, owner="aaaaaa", timeout_s=1.0)
    assert a.try_acquire() is not None
    age_lease(spool, by_s=5.0)
    assert a.heartbeat() is True  # utime refreshes the mtime
    b = ha.LeaderLease(spool, owner="bbbbbb", timeout_s=1.0)
    assert b.try_acquire() is None, "a heartbeated lease is not stale"


# -------------------------------------------------------------- journal


def test_journal_replay_idempotent(tmp_path):
    spool = Spool(str(tmp_path / "spool"))
    j = ha.IntakeJournal(spool)
    ticket = {"size": POP, "genome_len": LEN, "n": 3, "seed": 1}
    for i in range(3):
        j.record(f"t{i:05d}-x", dict(ticket, seed=i), tenant=None,
                 priority=0, trace_id=None, epoch=1)
    # duplicate record of an existing tid: entries() still dedupes
    j.record("t00001-x", dict(ticket, seed=1), tenant=None,
             priority=0, trace_id=None, epoch=1)
    first = [e["tid"] for e in j.entries()]
    second = [e["tid"] for e in j.entries()]
    assert first == second == ["t00000-x", "t00001-x", "t00002-x"]
    assert j.depth() == 3
    j.retire("t00001-x")
    assert [e["tid"] for e in j.entries()] == ["t00000-x", "t00002-x"]
    j.retire("t00001-x")  # idempotent
    assert j.depth() == 2


def test_fleet_replay_admits_exactly_once(tmp_path):
    spool_dir = str(tmp_path / "spool")
    a = Fleet(spool_dir, "onemax", config=CFG, fleet=ha_fc())
    assert a.is_leader and a.epoch == 1
    # durable-before-visible: submitting journals the ticket
    h = a.submit(FleetTicket(size=POP, genome_len=LEN, n=3, seed=7))
    assert a._journal.depth() == 1
    assert a.sched.depth() == 1
    # replaying over an already-admitted journal is a no-op
    admitted, skipped = a._replay_intake()
    assert (admitted, skipped) == (0, 0)
    assert a.sched.depth() == 1
    halt(a)  # A dies; its lease goes stale
    # a second candidate replays the same journal into its OWN sched
    b = Fleet(spool_dir, "onemax", config=CFG, fleet=ha_fc())
    assert not b.is_leader
    age_lease(a.spool, by_s=5.0)
    won = b._lease.try_acquire()
    assert won is not None and won["epoch"] == 2
    b._become_leader(won, during_init=True)  # no worker spawn in-test
    assert b.sched.depth() == 1, "journaled ticket re-admitted once"
    assert h.tid in b._handles
    admitted, skipped = b._replay_intake()
    assert (admitted, skipped) == (0, 0), "second replay is a no-op"
    halt(b)


# -------------------------------------------------------------- fencing


def test_zombie_leader_batch_fenced(tmp_path):
    spool_dir = str(tmp_path / "spool")
    a = Fleet(spool_dir, "onemax", config=CFG, fleet=ha_fc())
    assert a.is_leader
    a.submit(FleetTicket(size=POP, genome_len=LEN, n=3, seed=7))
    # SIGSTOP analog: freeze A's monitor so it neither heartbeats nor
    # notices the coming seizure
    a._stop_monitor.set()
    a._wake.set()
    if a._monitor is not None:
        a._monitor.join(timeout=10)
    # the standby seizes the stale lease while A is stopped
    b = Fleet(spool_dir, "onemax", config=CFG, fleet=ha_fc())
    age_lease(a.spool, by_s=5.0)
    won = b._lease.try_acquire()
    b._become_leader(won, during_init=True)
    assert b.epoch == 2
    # A resumes, still believing it leads, and releases its batch with
    # the stale epoch
    assert a.is_leader  # the zombie has not noticed yet
    released = a._schedule(urgent=True)
    assert released >= 1
    names = a.spool.pending_batches()
    assert names
    batch = a.spool.read_json(a.spool.path("pending", names[0]))
    assert batch["epoch"] == 1
    # a worker refuses it: claim removes the file, takes NO lease
    w = WorkerHarness(spool_dir, "wtest", heartbeat_s=0.2, poll_s=0.05)
    assert w.claim() is None
    assert a.spool.pending_batches() == []
    assert a.spool.claimed_batches() == []
    assert not os.path.exists(a.spool.lease_path(names[0]))
    # the zombie's own heartbeat discipline would now demote it
    assert a._lease.heartbeat() is False
    a._closed = True
    halt(b)


def test_adopted_batch_is_served_not_fenced(tmp_path):
    spool_dir = str(tmp_path / "spool")
    a = Fleet(spool_dir, "onemax", config=CFG, fleet=ha_fc())
    a.submit(FleetTicket(size=POP, genome_len=LEN, n=3, seed=7))
    a._schedule(urgent=True)  # batch released BEFORE the failover
    names = a.spool.pending_batches()
    assert names and a.spool.read_json(
        a.spool.path("pending", names[0]))["epoch"] == 1
    halt(a)  # A dies with its batch still pending
    b = Fleet(spool_dir, "onemax", config=CFG, fleet=ha_fc())
    age_lease(a.spool, by_s=5.0)
    b._become_leader(b._lease.try_acquire(), during_init=True)
    # adoption re-stamped the pending batch to the new epoch in place
    batch = b.spool.read_json(b.spool.path("pending", names[0]))
    assert batch["epoch"] == 2
    w = WorkerHarness(spool_dir, "wtest", heartbeat_s=0.2, poll_s=0.05)
    claimed = w.claim()
    assert claimed == names[0], "adopted batch must stay claimable"
    w._shutdown(clean=False)
    halt(b)


# ------------------------------------------------------------- failover


def test_failover_finishes_journaled_work_bit_identical(tmp_path):
    spool_dir = str(tmp_path / "spool")
    a = Fleet(spool_dir, "onemax", config=CFG, fleet=ha_fc())
    assert a.is_leader
    client = ha.SpoolClient(spool_dir)
    tid = client.submit(FleetTicket(size=POP, genome_len=LEN, n=4, seed=11))
    # A dies before ever admitting the client's ticket (never started)
    a._closed = True
    events_path = str(tmp_path / "events.jsonl")
    log = telemetry.EventLog(events_path)
    b = Fleet(spool_dir, "onemax", config=CFG, fleet=ha_fc(), events=log)
    assert not b.is_leader
    age_lease(b.spool, by_s=5.0)
    b.start()  # standby start: monitor only; takeover spawns workers
    try:
        wait_for(lambda: b.is_leader, timeout=30, what="takeover")
        assert b.epoch == 2 and b.failovers == 1
        res = client.result(tid, timeout=120)
        np.testing.assert_array_equal(res.genomes, engine_run(11, 4))
        st = fleet_status(spool_dir)
        ld = st["leadership"]
        assert ld["enabled"] and ld["epoch"] == 2
        assert ld["leader_pid"] == os.getpid()
    finally:
        b.close()
        log.close()
    records = telemetry.validate_log(events_path)
    kinds = [r["event"] for r in records]
    assert "leader_elect" in kinds
    assert "coordinator_failover" in kinds
    assert "intake_journal_replay" in kinds


# ---------------------------------------------------- config + plumbing


def test_coordinators_config_validation():
    with pytest.raises(ValueError):
        FleetConfig(coordinators=0)
    assert FleetConfig().coordinators == 1


def test_single_coordinator_spool_untouched(tmp_path):
    """coordinators=1 (the default) must keep the round-23 spool
    byte-compatible: no coord/ or intake/ directories, no epoch field
    in batch files, leadership disabled in fleet_status."""
    spool_dir = str(tmp_path / "spool")
    f = Fleet(spool_dir, "onemax", config=CFG,
              fleet=ha_fc(coordinators=1))
    assert f.is_leader and f.epoch == 0
    f.submit(FleetTicket(size=POP, genome_len=LEN, n=3, seed=7))
    f._schedule(urgent=True)
    assert not os.path.isdir(f.spool.path(ha.COORD_DIR))
    assert not os.path.isdir(f.spool.path(ha.INTAKE_DIR))
    names = f.spool.pending_batches()
    batch = f.spool.read_json(f.spool.path("pending", names[0]))
    assert "epoch" not in batch
    assert fleet_status(spool_dir)["leadership"] == {"enabled": False}
    halt(f)


def test_parse_coord_chaos():
    assert _parse_coord_chaos("") == []
    plan = _parse_coord_chaos("sigkill@batch_form:2")
    assert len(plan) == 1
    with pytest.raises(ValueError):
        _parse_coord_chaos("sigkill@nonsense:1")
    with pytest.raises(ValueError):
        _parse_coord_chaos("gibberish")


def test_status_carries_leadership_fields(tmp_path):
    spool_dir = str(tmp_path / "spool")
    f = Fleet(spool_dir, "onemax", config=CFG, fleet=ha_fc())
    st = f.status()
    coord = st["coordinator"]
    assert coord["coordinators"] == 2
    assert coord["is_leader"] is True
    assert coord["epoch"] == 1
    assert coord["failovers"] == 0
    f._closed = True
