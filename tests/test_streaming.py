"""Streaming evolution service (ISSUE 12).

The acceptance matrix of `libpga_tpu/streaming/`:

- a step()-only EvolutionSession is BIT-IDENTICAL to a same-seed
  PGA.run (final population AND telemetry history) — including when
  stepped in chunks, pooled, or co-batched in a SessionGroup;
- the make_run_loop injection slot folds told candidates over the
  worst rows with told-fitness override, and an empty fold (inj_n=0)
  is value-identical to the uninjected program;
- suspend -> resume (a fresh engine = a simulated fresh process) is
  bit-identical at any generation boundary, pending tells and all, and
  composes with pop_shards > 1 and GP genomes with zero special cases;
- the warm pool's hit path reuses engines and compiles 0 new programs;
- PBT is off by default and byte-inert when off; deterministic when on;
- the C bridge's sized-snapshot entry points honor the retry-once
  contract; fleet worker spawns propagate the parent's JAX config
  knobs.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libpga_tpu import (
    PGA,
    GPConfig,
    PBTConfig,
    PGAConfig,
    StreamingConfig,
    TelemetryConfig,
)
from libpga_tpu.engine import fold_injection, make_run_loop
from libpga_tpu.ops.crossover import uniform_crossover
from libpga_tpu.ops.mutate import make_point_mutate
from libpga_tpu.ops.step import make_breed
from libpga_tpu.streaming import (
    EnginePool,
    EvolutionSession,
    SessionGroup,
    SessionStore,
)
from libpga_tpu.utils import telemetry as T
from libpga_tpu.utils.metrics import Counters

CFG = PGAConfig(use_pallas=False)
TCFG = PGAConfig(use_pallas=False, telemetry=TelemetryConfig(history_gens=32))


def _engine(seed, size=128, genome_len=16, config=CFG, objective="onemax"):
    pga = PGA(seed=seed, config=config)
    h = pga.create_population(size, genome_len)
    pga.set_objective(objective)
    return pga, h


def _same_pop(a, b) -> bool:
    return np.array_equal(
        np.asarray(a.genomes), np.asarray(b.genomes)
    ) and np.array_equal(np.asarray(a.scores), np.asarray(b.scores))


# ------------------------------------------------------------ injection slot


class TestInjectionSlot:
    def _loop(self, inject_slots=None, hist=None):
        from libpga_tpu import objectives

        obj = objectives.get("onemax")
        breed3 = make_breed(uniform_crossover, make_point_mutate(0.01))
        return make_run_loop(
            obj, lambda g, s, k, mp: breed3(g, s, k), hist,
            inject_slots=inject_slots,
        )

    @pytest.mark.parametrize("hist", [None, 16])
    def test_empty_fold_is_value_identical(self, hist):
        plain = self._loop(hist=hist)
        inj = self._loop(inject_slots=4, hist=hist)
        g0 = jax.random.uniform(jax.random.key(3), (64, 8))
        key = jax.random.key(7)
        args = (g0, key, jnp.int32(4), jnp.float32(np.inf),
                jnp.zeros((1, 2), jnp.float32))
        a = plain(*args)
        b = inj(*args, jnp.zeros((4, 8)), jnp.full((4,), -jnp.inf),
                jnp.int32(0))
        for x, y in zip(a, b):
            # equal_nan: the history buffer's never-written rows are NaN
            assert np.array_equal(
                np.asarray(x), np.asarray(y), equal_nan=True
            )

    def test_fold_replaces_worst_and_overrides_scores(self):
        g = jnp.asarray(np.random.default_rng(0).uniform(size=(8, 4)),
                        jnp.float32)
        s = jnp.arange(8, dtype=jnp.float32)
        inj_g = jnp.full((2, 4), 0.5, jnp.float32)
        inj_s = jnp.asarray([100.0, 200.0], jnp.float32)
        g2, s2 = fold_injection(g, s, inj_g, inj_s, jnp.int32(2))
        s2 = np.asarray(s2)
        # worst rows (scores 0 and 1) were replaced, told scores installed
        assert set(np.asarray(jnp.sort(s2))[-2:]) == {100.0, 200.0}
        assert np.allclose(np.asarray(g2)[np.argmax(s2)], 0.5)
        # untouched rows intact
        assert float(s2.sum()) == float(2 + 3 + 4 + 5 + 6 + 7 + 300)

    def test_engine_run_inject(self):
        pga, h = _engine(0, 64, 8)
        told = np.full((3, 8), 0.75, np.float32)
        gens = pga.run(0, inject=(told, np.full(3, 50.0, np.float32)))
        assert gens == 0
        pop = pga.population(h)
        # a zero-generation inject run returns the folded state verbatim
        assert float(jnp.max(pop.scores)) == 50.0
        assert np.allclose(
            np.asarray(pop.genomes)[int(jnp.argmax(pop.scores))], 0.75
        )

    def test_engine_run_inject_validation(self):
        pga, h = _engine(1, 32, 8)
        with pytest.raises(ValueError, match="incompatible"):
            pga.run(1, inject=(np.zeros((2, 5), np.float32), np.zeros(2)))
        with pytest.raises(ValueError, match="fitnesses"):
            pga.run(1, inject=(np.zeros((2, 8), np.float32), np.zeros(3)))
        with pytest.raises(ValueError, match="cannot fold"):
            pga.run(1, inject=(
                np.zeros((64, 8), np.float32), np.zeros(64)
            ))


# ----------------------------------------------------------------- sessions


class TestSession:
    def test_step_only_bit_identity(self):
        s = EvolutionSession("onemax", 128, 16, seed=5, config=TCFG)
        s.step(6)
        pga, h = _engine(5, config=TCFG)
        pga.run(6)
        assert _same_pop(s.population(), pga.population(h))
        assert np.array_equal(s.history._rows, pga.history(h)._rows)

    def test_step_chunks_match_engine_runs(self):
        s = EvolutionSession("onemax", 64, 8, seed=9, config=CFG)
        s.step(3)
        s.step(4)
        pga, h = _engine(9, 64, 8)
        pga.run(3)
        pga.run(4)
        assert _same_pop(s.population(), pga.population(h))
        assert s.gens_done == 7

    def test_ask_before_fitness_returns_population_rows(self):
        s = EvolutionSession("onemax", 32, 8, seed=1, config=CFG)
        cand = s.ask(4)
        assert np.array_equal(
            cand, np.asarray(s.population().genomes[:4], np.float32)
        )

    def test_tell_folds_at_ask_boundary(self):
        s = EvolutionSession("onemax", 32, 8, seed=2, config=CFG)
        told = np.full((2, 8), 0.9, np.float32)
        s.tell(told, np.array([30.0, 40.0], np.float32))
        assert s.pending_tells == 2
        cand = s.ask(4)
        assert cand.shape == (4, 8)
        assert s.pending_tells == 0
        pop = s.population()
        assert float(jnp.max(pop.scores)) == 40.0  # told score installed

    def test_tell_folds_inside_step(self):
        s = EvolutionSession("onemax", 32, 8, seed=3, config=CFG)
        s.tell(np.full((1, 8), 0.5, np.float32), np.array([99.0]))
        gens = s.step(3, target=98.0)
        # the told fitness already beats the target at the boundary:
        # the loop exits before breeding a single generation.
        assert gens == 0
        assert float(jnp.max(s.population().scores)) == 99.0

    def test_tell_validation(self):
        s = EvolutionSession("onemax", 32, 8, seed=4, config=CFG)
        with pytest.raises(ValueError, match="incompatible"):
            s.tell(np.zeros((1, 5), np.float32), np.zeros(1))
        with pytest.raises(ValueError, match="fitnesses"):
            s.tell(np.zeros((2, 8), np.float32), np.zeros(1))
        with pytest.raises(ValueError, match="finite"):
            s.tell(np.zeros((1, 8), np.float32), np.array([np.nan]))

    def test_events_schema(self, tmp_path):
        events = str(tmp_path / "events.jsonl")
        cfg = PGAConfig(
            use_pallas=False,
            telemetry=TelemetryConfig(history_gens=8, events_path=events),
        )
        s = EvolutionSession("onemax", 32, 8, seed=0, config=cfg)
        s.tell(np.full((1, 8), 0.5, np.float32), np.array([1.0]))
        s.step(2)
        s.suspend(str(tmp_path / "s.ckpt.npz"))
        s.pga._events.close()
        records = T.validate_log(events)
        kinds = [r["event"] for r in records]
        assert "session_open" in kinds
        assert "session_fold" in kinds
        assert "session_suspend" in kinds
        fold = next(r for r in records if r["event"] == "session_fold")
        assert fold["folded"] == 1 and fold["session"] == s.sid


# ----------------------------------------------------------- suspend/resume


class TestSuspendResume:
    def test_bit_identity_across_simulated_process(self, tmp_path):
        path = str(tmp_path / "tenant.ckpt.npz")
        s = EvolutionSession("onemax", 64, 8, seed=11, config=TCFG)
        s.step(3)
        s.suspend(path)
        # a fresh resume is a simulated different process: nothing is
        # shared with the original but the files.
        r = EvolutionSession.resume(path, objective="onemax", config=TCFG)
        s.step(4)
        r.step(4)
        assert _same_pop(s.population(), r.population())
        assert np.array_equal(s.history._rows, r.history._rows)
        assert r.gens_done == 7 and r.sid == s.sid

    def test_resume_reads_meta_objective_and_config(self, tmp_path):
        path = str(tmp_path / "named.ckpt.npz")
        s = EvolutionSession(
            "sphere", 32, 8, seed=2,
            config=PGAConfig(use_pallas=False, elitism=2,
                             selection="truncation"),
        )
        s.step(2)
        s.suspend(path)
        r = EvolutionSession.resume(path)  # objective + config from meta
        assert r.pga.config.elitism == 2
        assert r.pga.config.selection == "truncation"
        s.step(2)
        r.step(2)
        assert _same_pop(s.population(), r.population())

    def test_pending_tells_roundtrip(self, tmp_path):
        path = str(tmp_path / "tells.ckpt.npz")
        s = EvolutionSession("onemax", 32, 8, seed=3, config=CFG)
        s.tell(np.full((2, 8), 0.25, np.float32), np.array([7.0, 8.0]))
        s.suspend(path)
        r = EvolutionSession.resume(path, objective="onemax", config=CFG)
        assert r.pending_tells == 2
        s.step(3)
        r.step(3)
        assert _same_pop(s.population(), r.population())

    def test_uncommitted_resume_raises(self, tmp_path):
        path = str(tmp_path / "never.ckpt.npz")
        with pytest.raises(FileNotFoundError, match="never committed"):
            EvolutionSession.resume(path, objective="onemax")

    @pytest.mark.skipif(
        jax.device_count() < 2, reason="needs a multi-device platform"
    )
    def test_composes_with_pop_shards(self, tmp_path):
        # zero special cases: the sharded engine checkpoints through the
        # same save/restore, the session layer does nothing extra.
        cfg = PGAConfig(use_pallas=False, pop_shards=2)
        path = str(tmp_path / "sharded.ckpt.npz")
        s = EvolutionSession("onemax", 64, 8, seed=4, config=cfg)
        s.step(2)
        s.suspend(path)
        r = EvolutionSession.resume(path, objective="onemax", config=cfg)
        s.step(2)
        r.step(2)
        a, b = s.population(), r.population()
        assert np.array_equal(np.asarray(a.genomes), np.asarray(b.genomes))

    def test_composes_with_gp_genomes(self, tmp_path):
        from libpga_tpu.gp import encoding as enc
        from libpga_tpu.gp import operators as gpo
        from libpga_tpu.gp.sr import make_dataset, symbolic_regression

        gp = GPConfig(max_nodes=8, n_vars=2)
        X, y = make_dataset(lambda a, b: a * a + b, n_samples=16, n_vars=2)
        obj = symbolic_regression(X, y, gp=gp)
        genomes = enc.random_population(jax.random.key(0), 64, gp)

        def build():
            return EvolutionSession(
                obj,
                genomes=genomes,
                config=PGAConfig(use_pallas=False, elitism=2),
                crossover=gpo.make_subtree_crossover(gp),
                mutate=gpo.make_gp_mutate(gp, 0.4, 0.6),
            )

        path = str(tmp_path / "gp.ckpt.npz")
        s = build()
        s.step(2)
        s.suspend(path)
        # GP operators are opaque callables: re-provide at resume.
        r = EvolutionSession.resume(
            path, objective=obj,
            config=PGAConfig(use_pallas=False, elitism=2),
            crossover=gpo.make_subtree_crossover(gp),
            mutate=gpo.make_gp_mutate(gp, 0.4, 0.6),
        )
        s.step(2)
        r.step(2)
        assert _same_pop(s.population(), r.population())


# ---------------------------------------------------------------- warm pool


class TestEnginePool:
    def test_hit_reuses_engine_and_compiles_nothing(self):
        pool = EnginePool(config=CFG, counters=Counters())
        w1 = pool.acquire("onemax", 64, 8, seed=3)
        w1.step(2)
        eng = w1.pga
        programs = len(eng._compiled)
        pool.release(w1)
        w2 = pool.acquire("onemax", 64, 8, seed=12)
        assert w2.pga is eng  # the warm engine itself came back
        w2.step(2)
        assert len(eng._compiled) == programs  # 0 new programs
        assert pool.stats()["hits"] == 1

    def test_pooled_session_bit_identical_to_cold(self):
        pool = EnginePool(config=CFG, counters=Counters())
        w1 = pool.acquire("onemax", 64, 8, seed=3)
        w1.step(2)
        pool.release(w1)
        w2 = pool.acquire("onemax", 64, 8, seed=3)
        w2.step(2)
        cold = EvolutionSession("onemax", 64, 8, seed=3, config=CFG)
        cold.step(2)
        assert _same_pop(w2.population(), cold.population())

    def test_prewarm_counts_and_signature_separation(self):
        pool = EnginePool(config=CFG, counters=Counters())
        pool.prewarm("onemax", 32, 8)
        assert pool.stats()["prewarms"] == 1
        w = pool.acquire("onemax", 32, 8, seed=0)
        assert pool.stats()["hits"] == 1  # the prewarmed engine
        # a different shape is a different signature: miss
        w2 = pool.acquire("onemax", 64, 8, seed=0)
        assert pool.stats()["misses"] == 1
        pool.release(w)
        pool.release(w2)
        assert pool.stats()["idle"] == 2

    def test_release_foreign_session_rejected(self):
        pool = EnginePool(config=CFG, counters=Counters())
        s = EvolutionSession("onemax", 32, 8, seed=0, config=CFG)
        with pytest.raises(ValueError, match="not acquired"):
            pool.release(s)

    def test_capacity_bounds_idle_engines(self):
        pool = EnginePool(
            config=CFG, counters=Counters(),
            streaming=StreamingConfig(pool_capacity=1, prewarm=False),
        )
        a = pool.acquire("onemax", 32, 8, seed=0)
        b = pool.acquire("onemax", 32, 8, seed=1)
        pool.release(a)
        pool.release(b)  # beyond capacity: dropped
        assert pool.stats()["idle"] == 1


# -------------------------------------------------------------- group + PBT


class TestSessionGroup:
    def _sessions(self, n, base_seed, config=CFG):
        return [
            EvolutionSession("onemax", 64, 8, seed=base_seed + i,
                             config=config)
            for i in range(n)
        ]

    def test_group_step_bit_identical_to_solo(self):
        grouped = self._sessions(4, 10)
        solo = self._sessions(4, 10)
        SessionGroup(grouped).step(3)
        for s in solo:
            s.step(3)
        for a, b in zip(grouped, solo):
            assert _same_pop(a.population(), b.population())
            assert a.gens_done == b.gens_done == 3

    def test_group_step_with_history(self):
        grouped = self._sessions(2, 20, config=TCFG)
        solo = self._sessions(2, 20, config=TCFG)
        SessionGroup(grouped).step(4)
        for s in solo:
            s.step(4)
        for a, b in zip(grouped, solo):
            assert np.array_equal(a.history._rows, b.history._rows)

    def test_group_folds_tells_like_solo(self):
        grouped = self._sessions(2, 30)
        solo = self._sessions(2, 30)
        told = np.full((2, 8), 0.8, np.float32)
        fits = np.array([60.0, 70.0], np.float32)
        grouped[1].tell(told, fits)
        solo[1].tell(told, fits)
        SessionGroup(grouped, tell_slots=2).step(3)
        for s in solo:
            s.step(3)
        for a, b in zip(grouped, solo):
            assert _same_pop(a.population(), b.population())

    def test_mixed_signature_rejected(self):
        a = EvolutionSession("onemax", 64, 8, seed=0, config=CFG)
        b = EvolutionSession("onemax", 32, 8, seed=0, config=CFG)
        with pytest.raises(ValueError, match="signature"):
            SessionGroup([a, b])

    def test_pbt_off_is_inert(self):
        grouped = self._sessions(4, 40)
        g = SessionGroup(grouped)  # pbt defaults off
        before = [g.mutation_params(i) for i in range(4)]
        g.step(6)
        assert [g.mutation_params(i) for i in range(4)] == before

    def test_pbt_adapts_deterministically(self):
        def run():
            sessions = self._sessions(4, 50)
            g = SessionGroup(
                sessions,
                streaming=StreamingConfig(
                    pbt=PBTConfig(epoch_gens=2, exploit_frac=0.25)
                ),
            )
            g.step(6)
            return (
                [g.mutation_params(i) for i in range(4)],
                [np.asarray(s.population().genomes) for s in sessions],
            )

        p1, g1 = run()
        p2, g2 = run()
        assert p1 == p2
        for a, b in zip(g1, g2):
            assert np.array_equal(a, b)
        # something actually moved
        assert len(set(r for r, _ in p1)) > 1


# -------------------------------------------------------------------- store


class TestSessionStore:
    def test_roundtrip_list_discard(self, tmp_path):
        store = SessionStore(str(tmp_path / "sessions"))
        s = EvolutionSession("onemax", 32, 8, seed=0, config=CFG)
        s.step(2)
        store.suspend(s)
        assert store.list() == [s.sid]
        assert store.meta(s.sid)["gens_done"] == 2
        r = store.resume(s.sid, objective="onemax", config=CFG)
        s.step(2)
        r.step(2)
        assert _same_pop(s.population(), r.population())
        store.discard(s.sid)
        assert store.list() == []

    def test_fleet_spool_hosts_sessions(self, tmp_path):
        from libpga_tpu.serving.fleet import Spool

        spool = Spool(str(tmp_path / "spool"))
        assert os.path.isdir(spool.path("sessions"))

    def test_invalid_sid_rejected(self, tmp_path):
        store = SessionStore(str(tmp_path / "s"))
        with pytest.raises(ValueError):
            store.path("../escape")


# ------------------------------------------------------- satellites (12.x)


class TestJaxEnvKnobs:
    def test_parent_config_knobs_propagate(self):
        from libpga_tpu.serving.fleet import _jax_env_knobs

        knobs = _jax_env_knobs()
        # conftest flips threefry partitionability PROGRAMMATICALLY —
        # exactly the knob class that silently diverges worker RNG.
        assert knobs["JAX_THREEFRY_PARTITIONABLE"] == "1"
        assert knobs["JAX_ENABLE_X64"] == "0"
        assert knobs.get("JAX_PLATFORMS") == "cpu"


class TestSizedSnapshots:
    def test_retry_once_contract(self):
        from libpga_tpu import capi_bridge as B
        from libpga_tpu.utils import metrics as M

        need = len(B.metrics_snapshot_json(0))  # size query: parks
        # grow the snapshot between query and fill — the race the
        # contract covers.
        M.REGISTRY.counter(
            "test.retry_once.growth", label="x" * 64
        ).bump()
        filled = B.metrics_snapshot_json(need + 1)
        assert len(filled) == need  # parked rendering, not the grown one
        # next call re-renders fresh (the park was consumed)
        assert len(B.metrics_snapshot_json(10 ** 9)) >= need

    def test_truncated_fill_reparks(self):
        from libpga_tpu import capi_bridge as B

        tiny = B.metrics_snapshot_json(8)  # too small: parks
        again = B.metrics_snapshot_json(len(tiny) + 1)
        assert again == tiny

    def test_session_snapshot_lists_sessions(self):
        from libpga_tpu import capi_bridge as B

        h = B.session_open("onemax", 32, 8, 5)
        try:
            B.session_step(h, 2, 0, 0.0)
            snap = json.loads(B.session_snapshot_json(0).decode())
            mine = [s for s in snap["sessions"] if s["handle"] == h]
            assert mine and mine[0]["gens_done"] == 2
            assert "pool" in snap
        finally:
            B.session_close(h)

    def test_bridge_session_roundtrip(self, tmp_path):
        from libpga_tpu import capi_bridge as B

        h = B.session_open("onemax", 32, 8, 7)
        cand = np.frombuffer(
            B.session_ask(h, 4), np.float32
        ).reshape(4, 8)
        B.session_tell(
            h, cand.tobytes(), cand.sum(axis=1).tobytes(), 4
        )
        assert B.session_step(h, 3, 0, 0.0) == 3
        best = np.frombuffer(B.session_best(h), np.float32)
        assert best.shape == (9,) and 0.0 <= best[0] <= 8.0
        path = str(tmp_path / "abi.ckpt.npz")
        assert B.session_suspend(h, path) == 0
        h2 = B.session_resume(path, "")
        assert B.session_step(h, 2, 0, 0.0) == 2
        assert B.session_step(h2, 2, 0, 0.0) == 2
        b1 = np.frombuffer(B.session_best(h), np.float32)
        b2 = np.frombuffer(B.session_best(h2), np.float32)
        assert np.array_equal(b1, b2)
        assert B.session_close(h) == 0
        assert B.session_close(h2) == 0


class TestStreamingConfig:
    def test_validation(self):
        with pytest.raises(ValueError):
            StreamingConfig(pool_capacity=0)
        with pytest.raises(ValueError):
            StreamingConfig(max_tell_slots=0)
        with pytest.raises(ValueError):
            PBTConfig(epoch_gens=0)
        with pytest.raises(ValueError):
            PBTConfig(exploit_frac=0.9)
        with pytest.raises(ValueError):
            PBTConfig(explore_factor=1.0)
        with pytest.raises(ValueError):
            PBTConfig(rate_bounds=(0.5, 0.1))
