"""Performance observatory tests (ISSUE 17).

Four legs: the analytic cost model against hand-computed FLOPs/bytes
(the acceptance check — numbers derived from the kernel structure, not
from the code under test), the drift-floor-aware regression detector,
the append-only associatively-mergeable perf history, and the
program-report / stage-attribution plumbing end to end.
"""

import glob
import json
import math
import os

import jax.numpy as jnp
import pytest

from libpga_tpu import PGA, PGAConfig, TelemetryConfig, perf
from libpga_tpu.perf import history as H
from libpga_tpu.utils import metrics as M
from libpga_tpu.utils import telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------ cost model


def test_breed_report_hand_computed_f32():
    """The flagship 1Mx100 f32 shape, FLOPs/bytes derived by hand.

    Plan (pure resolution, no hardware): K=512, D=8 ping-pong, Lp=128
    (100 genes padded to the lane). Selection is 4 (K,K)x(K,Lp)
    matmuls per deme step (f32 hi/lo split), P/K deme steps per
    generation: flops = P*K*Lp*2*4. HBM floor: one read + one write of
    the (P,Lp) population plus two (P,) f32 score vectors per
    generation.
    """
    r = perf.breed_report(1 << 20, 100, gene_dtype=jnp.float32,
                          device_kind="TPU v5e")
    P, K, Lp = 1 << 20, 512, 128
    assert r["path"] != "xla" and r["plan"]["deme_size"] == K
    assert r["flops_per_gen"] == P * K * Lp * 2 * 4 == 549755813888
    assert (r["hbm_bytes_per_gen"]
            == 2 * P * Lp * 4 + 2 * P * 4 == 1082130432)
    # v5e roofline: 197 TFLOP/s, 819 GB/s. This shape is compute-bound.
    t_compute = 549755813888 / 197e12
    t_memory = 1082130432 / 819e9
    assert t_compute > t_memory and r["bound"] == "compute"
    assert r["roofline_gens_per_sec"] == pytest.approx(1.0 / t_compute)
    assert r["arithmetic_intensity"] == pytest.approx(
        549755813888 / 1082130432)


def test_breed_report_hand_computed_bf16():
    """bf16 halves both the matmul count (native MXU, no hi/lo split:
    2 instead of 4) and the gene bytes — so FLOPs halve and the HBM
    floor drops to 2*P*Lp*2 + scores."""
    r = perf.breed_report(1 << 20, 100, gene_dtype=jnp.bfloat16,
                          device_kind="TPU v5e")
    P, K, Lp = 1 << 20, 512, 128
    assert r["flops_per_gen"] == P * K * Lp * 2 * 2 == 274877906944
    assert r["hbm_bytes_per_gen"] == 2 * P * Lp * 2 + 2 * P * 4
    assert r["roofline_gens_per_sec"] == pytest.approx(
        197e12 / 274877906944)


def test_breed_report_mfu_matches_historical_artifact():
    """perf.achieved reproduces the r05 BENCH artifact's MFU: 140.0
    gens/s on the f32 1Mx100 shape was published as mfu 0.3907."""
    r = perf.breed_report(1 << 20, 100, gene_dtype=jnp.float32,
                          device_kind="TPU v5e")
    a = perf.achieved(r, 140.0)
    assert a["flops_frac_of_peak"] == pytest.approx(0.3907, abs=5e-4)
    assert a["roofline_frac"] == pytest.approx(140.0 * 549755813888 / 197e12)


def test_gp_report_hand_computed():
    """GP-eval FLOPs from the dense mask-only lattice: per (genome,
    sample, node) the evaluator does 3 stack passes x 2 ops (6*S) plus
    2 ops per op-family candidate plane — n_ops planes, plus the LIT
    plane when the eval-time optimizer is on (the GPConfig default).
    Without a measured live length the model charges the full
    max_nodes trip."""
    from libpga_tpu.gp.encoding import GPConfig

    gp = GPConfig(max_nodes=64)
    P = 512
    r = perf.gp_report(P, gp, 64)
    S = r["plan"]["stack_depth"]
    # The kernel computes PADDED sample lanes, not the raw n_samples —
    # 64 samples occupy a full 128-lane block — so the FLOPs model
    # charges batch_lanes. gp_report normalizes to the per-"generation"
    # (= per full-population eval) keys so roofline/achieved work
    # identically for both report kinds.
    B = r["batch_lanes"]
    assert B == 128
    assert r["tokens_per_program"] == gp.max_nodes
    assert r["flops_per_gen"] == gp.max_nodes * P * B * (
        6 * S + 2 * (gp.n_ops + 1))
    assert r["report"] == "gp_eval" and r["roofline_gens_per_sec"] > 0

    # The optimizer-off twin prices the legacy lattice exactly as
    # before — no LIT plane, full-cap trip.
    gp_off = GPConfig(max_nodes=64, optimize=False)
    r_off = perf.gp_report(P, gp_off, 64)
    assert r_off["flops_per_gen"] == gp.max_nodes * P * B * (
        6 * S + 2 * gp.n_ops)

    # A measured mean live length shrinks the charged trip count —
    # the roofline stays honest for the compacted fast path.
    r_live = perf.gp_report(P, gp, 64, live_length=16.0)
    assert r_live["tokens_per_program"] == 16.0
    assert r_live["flops_per_gen"] == int(round(16.0 * P * B * (
        6 * S + 2 * (gp.n_ops + 1))))


def test_breed_report_xla_fallback_has_no_roofline():
    """A shape the fused kernel refuses (deme floor) degrades to an
    xla report without fabricated roofline numbers."""
    r = perf.breed_report(64, 8, gene_dtype=jnp.float32)
    assert r["path"] == "xla"
    assert "roofline_gens_per_sec" not in r


def test_device_peaks_unknown_kind_is_flagged():
    flops, hbm, assumed = perf.device_peaks("TPU v99")
    assert assumed  # fell back to the default chip, and says so
    assert flops > 0 and hbm > 0
    assert not perf.device_peaks("TPU v4")[2]


# -------------------------------------------------------------- detector


def test_detector_inside_drift_floor_abstains():
    """A 3.9% dip is indistinguishable from same-process CPU drift
    (the ~4% floor measured in BENCH_r06) — must not convict."""
    base = [100.0, 101.0, 99.5, 100.5, 100.2]
    v = perf.detect(base, 100.2 * (1 - 0.039))
    assert not v.regressed and v.threshold >= perf.DRIFT_FLOOR


def test_detector_outside_drift_floor_convicts():
    base = [100.0, 101.0, 99.5, 100.5, 100.2]
    v = perf.detect(base, 100.2 * (1 - 0.10))
    assert v.regressed and "breaches" in v.reason


def test_detector_noisy_baseline_widens_bar():
    """The bar is max(floor, 2*rel_ci): a baseline whose half-IQR is
    10% of the median gets a 20% bar, so a 15% dip — a conviction on a
    tight baseline — is acquitted here."""
    base = [80.0, 90.0, 100.0, 110.0, 120.0]
    v = perf.detect(base, 85.0)
    assert v.rel_ci == pytest.approx(0.10)
    assert v.threshold == pytest.approx(0.20)
    assert v.threshold > perf.DRIFT_FLOOR
    assert not v.regressed
    assert perf.detect(base, 40.0).regressed


def test_detector_abstains_below_min_samples():
    v = perf.detect([100.0, 101.0], 50.0)
    assert not v.regressed and "baselining" in v.reason


def test_detector_drops_non_finite_baseline_points():
    base = [100.0, float("nan"), 101.0, float("inf"), 99.0]
    v = perf.detect(base, 80.0)
    assert v.n_baseline == 3 and v.regressed
    v2 = perf.detect([float("nan")] * 5, 80.0)
    assert not v2.regressed and "baselining" in v2.reason


def test_detector_identical_baseline_iqr_zero():
    """Zero spread -> rel_ci 0 -> the bar is exactly the floor."""
    v = perf.detect([100.0] * 5, 90.0)
    assert v.rel_ci == 0.0 and v.threshold == perf.DRIFT_FLOOR
    assert v.regressed


def test_detector_degenerate_baseline_abstains():
    assert not perf.detect([0.0] * 5, 10.0).regressed
    assert not perf.detect([-5.0, -5.0, -5.0], 1.0).regressed


def test_detector_lower_is_better():
    base = [10.0, 10.2, 9.9, 10.1]
    v = perf.detect(base, 12.0, metric="ms_per_gen",
                    higher_is_better=False)
    assert v.regressed
    assert not perf.detect(base, 9.0, higher_is_better=False).regressed


# --------------------------------------------------------------- history


def _sample(metric="gens", value=1.0, rnd=1, run=1, src="a"):
    return H.PerfSample(
        key=H.PerfKey("cpu", "cpu", "64x8", "single"),
        metric=metric, value=value, round=rnd, run_id=run, source=src,
    )


def test_history_merge_is_associative_and_commutative():
    def mk(*specs):
        h = H.PerfHistory()
        for s in specs:
            h.add(s)
        return h

    a = mk(_sample(run=1), _sample(run=2, value=2.0))
    b = mk(_sample(run=2, value=2.0), _sample(run=3, value=3.0))
    c = mk(_sample(run=4, value=4.0), _sample(metric="other"))

    left = a.merge(b).merge(c)
    right = a.merge(b.merge(c))
    assert left.to_json() == right.to_json()
    assert a.merge(b).to_json() == b.merge(a).to_json()
    assert len(left) == 5  # the shared run=2 sample deduped
    # merge() is non-destructive
    assert len(a) == 2 and len(b) == 2


def test_history_conflicting_duplicate_resolves_by_total_order():
    """Same identity, different value (a re-written artifact): both
    merge orders must pick the SAME winner or merging isn't a CRDT."""
    a = H.PerfHistory(); a.add(_sample(value=1.0))
    b = H.PerfHistory(); b.add(_sample(value=2.0))
    ab = a.merge(b).to_json()
    ba = b.merge(a).to_json()
    assert ab == ba


def test_history_atomic_save_and_load(tmp_path):
    h = H.PerfHistory()
    h.add(_sample())
    path = str(tmp_path / "hist.json")
    h.save(path)
    assert not glob.glob(str(tmp_path / "*.tmp"))  # no torn residue
    h2 = H.PerfHistory.load(path)
    assert h2.to_json() == h.to_json()


def test_history_refuses_newer_schema(tmp_path):
    h = H.PerfHistory()
    h.add(_sample())
    d = h.to_json()
    d["schema_version"] = H.SCHEMA_VERSION + 1
    p = tmp_path / "future.json"
    p.write_text(json.dumps(d))
    with pytest.raises(H.PerfSchemaError):
        H.PerfHistory.load(str(p))


def test_history_torn_file_skip_and_report(tmp_path):
    good = tmp_path / "good.json"
    h = H.PerfHistory()
    h.add(_sample())
    h.save(str(good))
    torn = tmp_path / "torn.json"
    torn.write_text(good.read_text()[: len(good.read_text()) // 2])
    merged, skipped = H.merge_files([str(good), str(torn)])
    assert len(merged) == 1
    assert len(skipped) == 1 and "torn.json" in skipped[0]
    with pytest.raises(H.PerfHistoryError):
        merged.ingest_file(str(torn))


def test_ingest_refuses_future_artifact_schema():
    h = H.PerfHistory()
    with pytest.raises(H.PerfHistoryError, match="newer than supported"):
        h.ingest_artifact(
            {"schema_version": H.MAX_ARTIFACT_SCHEMA + 1, "x": 1.0},
            source="BENCH_r99.json",
        )


def test_backfill_all_historical_artifacts_ingest():
    """The acceptance check: every committed BENCH_r*.json (three
    artifact generations) lands in one schema-valid history DB with
    exactly one primary sample per artifact."""
    paths = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))
    assert len(paths) >= 15
    h = H.PerfHistory()
    per_round_primaries = {}
    for p in paths:
        added = h.ingest_file(p)
        assert added, f"{p} produced no samples"
        prim = [s for s in added if s.note == "primary"]
        assert len(prim) == 1, f"{p}: primaries {prim}"
        per_round_primaries[prim[0].round] = prim[0]
    assert set(per_round_primaries) == set(range(1, len(paths) + 1))
    # r01-r06 predate provenance stamping and must say so, not guess.
    assert per_round_primaries[1].key.backend == "unstamped"
    assert per_round_primaries[15].key.backend == "cpu"
    # round-trips through the versioned serialization
    assert (H.PerfHistory.from_json(h.to_json()).to_json()
            == h.to_json())


def test_series_orders_by_round_then_run():
    h = H.PerfHistory()
    h.add(_sample(rnd=2, run=1, value=2.0))
    h.add(_sample(rnd=1, run=5, value=1.0))
    h.add(_sample(rnd=2, run=0, value=3.0, src="b"))
    vals = [s.value for s in h.series(
        H.PerfKey("cpu", "cpu", "64x8", "single"), "gens")]
    assert vals == [1.0, 3.0, 2.0]


# ----------------------------------------- program report + attribution


def _tiny_pga(events_path=None):
    tel = (TelemetryConfig(history_gens=4, events_path=events_path)
           if events_path else None)
    pga = PGA(seed=3, config=PGAConfig(use_pallas=False, telemetry=tel))
    h = pga.create_population(64, 16)
    pga.set_objective("onemax")
    return pga, h


def test_program_report_emits_valid_event(tmp_path):
    path = str(tmp_path / "events.jsonl")
    pga, h = _tiny_pga(path)
    r = pga.program_report(h)
    assert r["pop"] == 64 and r["genome_len"] == 16
    assert r["dispatch_path"] == "xla"  # no TPU in this harness
    assert r["key"].startswith("pop=64|len=16|dtype=float32|")
    recs = telemetry.validate_log(path)  # raises on schema break
    pr = [x for x in recs if x["event"] == "perf_report"]
    assert pr and pr[0]["key"] == r["key"]


def test_program_report_achieved_fraction(tmp_path):
    pga, h = _tiny_pga()
    r = pga.program_report(h, measured_gens_per_sec=100.0)
    assert r["measured_gens_per_sec"] == 100.0
    if "roofline_gens_per_sec" in r:
        assert r["roofline_frac"] == pytest.approx(
            100.0 / r["roofline_gens_per_sec"])


def test_span_populates_stage_ms_and_breakdown():
    M.REGISTRY.reset()
    pga, _ = _tiny_pga()
    pga.run(3)
    shares = perf.stage_shares()
    assert shares, "pga.run produced no perf.stage_ms series"
    assert math.isclose(sum(shares.values()), 1.0, rel_tol=1e-9)
    snap = M.REGISTRY.snapshot()
    names = {r["name"] for r in snap["histograms"]}
    assert "perf.stage_ms" in names
    # ... and the rendering is scrape-able (the stage-17 lint).
    assert M.lint_prometheus(M.prometheus_text(snap)) == []


def test_stage_breakdown_folds_unknown_stage_to_host():
    snap = {"histograms": [
        {"name": "perf.stage_ms", "labels": {"stage": "evaluate"},
         "sum": 30.0, "count": 3},
        {"name": "perf.stage_ms", "labels": {"stage": "mystery"},
         "sum": 10.0, "count": 1},
    ], "counters": [], "gauges": []}
    shares = perf.stage_shares(snap)
    assert shares["eval"] == pytest.approx(0.75)
    assert shares["host"] == pytest.approx(0.25)


def test_bench_single_derived_uses_shared_cost_model():
    import bench

    d = bench.single_derived(jnp.float32, 140.0)
    assert d["mfu"] == pytest.approx(0.3907, abs=5e-4)
    assert d["roofline_bound"] == "compute"
    assert d["selection_matmul_mfu"] == d["mfu"]


def test_bench_provenance_stamps_rev_and_run_id():
    import bench

    prov = bench.provenance()
    assert prov["schema_version"] == bench.SCHEMA_VERSION == 2
    assert isinstance(prov["run_id"], int) and prov["run_id"] > 0
    assert prov["git_rev"]  # short rev or "unknown", never empty
