"""Tenant-attributed observability (ISSUE 14).

Covers the identity layer (validation, the default-anon rule), the
registry's label-cardinality guard, the exposition lint's label-value
checks, the multi-window burn-rate monitor and its SLOConfig wiring,
tenant plumbing through the serving queue, per-tenant snapshot merging
across the fleet spool flush/merge path, streaming session lifecycle
tracing, and the acceptance pin that attribution is host-side only
(tenant on/off lowers byte-identical StableHLO, zero extra compiles).
"""

from __future__ import annotations

import json
import os
import warnings

import numpy as np
import pytest

from libpga_tpu.config import BurnRateConfig, PGAConfig, SLOConfig
from libpga_tpu.utils import metrics as M
from libpga_tpu.utils import telemetry as T
from libpga_tpu.utils.tenancy import ANON, OVERFLOW, validate_tenant

CFG = PGAConfig(use_pallas=False)


# ------------------------------------------------------------- identity


class TestValidateTenant:
    def test_none_is_anon(self):
        assert validate_tenant(None) == ANON == "anon"

    @pytest.mark.parametrize(
        "ok", ["anon", "team-a", "u.123", "A_b-c.d", "x" * 64]
    )
    def test_label_safe_ids_pass(self, ok):
        assert validate_tenant(ok) == ok

    @pytest.mark.parametrize(
        "bad",
        ["", "a b", "x" * 65, "naïve", 'q"uote', "a/b", "-lead", ".lead"],
    )
    def test_unsafe_ids_rejected(self, bad):
        with pytest.raises(ValueError, match="invalid tenant id"):
            validate_tenant(bad)

    def test_reserved_prefix_rejected(self):
        with pytest.raises(ValueError, match="reserved"):
            validate_tenant(OVERFLOW)


# ---------------------------------------------------- cardinality guard


class TestCardinalityGuard:
    def _registry(self, limit=3):
        r = M.MetricsRegistry()
        r.label_cardinality_limit = limit
        return r

    def test_overflow_bucket_and_warn_once(self):
        r = self._registry()
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            for i in range(8):
                r.counter("x.hits", tenant=f"t{i}").bump()
        guard_warnings = [
            x for x in w if "distinct values" in str(x.message)
        ]
        assert len(guard_warnings) == 1  # once per label name, not per value
        snap = r.snapshot()
        series = {
            tuple(sorted(c["labels"].items())): c["value"]
            for c in snap["counters"]
        }
        # First 3 values kept their own series; the other 5 share one.
        assert series[(("tenant", "t0"),)] == 1
        assert series[(("tenant", OVERFLOW),)] == 5
        assert r.label_overflow() == {"tenant": 5}

    def test_overflow_gauge_in_snapshot(self):
        r = self._registry(limit=1)
        r.gauge("d", tenant="a").set(1)
        r.gauge("d", tenant="b").set(1)
        recs = [
            g for g in r.snapshot()["gauges"]
            if g["name"] == "registry.label_overflow"
        ]
        assert recs == [
            {"name": "registry.label_overflow",
             "labels": {"label": "tenant"}, "value": 1.0}
        ]

    def test_existing_values_unaffected_past_cap(self):
        r = self._registry(limit=2)
        a = r.counter("c", tenant="a")
        r.counter("c", tenant="b")
        r.counter("c", tenant="c")  # overflows
        assert r.counter("c", tenant="a") is a  # still its own series

    def test_reset_clears_guard_state(self):
        r = self._registry(limit=1)
        r.counter("c", tenant="a")
        r.counter("c", tenant="b")
        r.reset()
        assert r.label_overflow() == {}
        r.counter("c", tenant="z")  # fits again after reset


# ------------------------------------------------------ exposition lint


class TestExpositionLint:
    def test_clean_labeled_exposition_passes(self):
        r = M.MetricsRegistry()
        r.counter("ok.hits", tenant="team-a").bump()
        r.histogram("ok.ms", tenant="team-a").observe(3.0)
        assert M.lint_prometheus(M.prometheus_text(r.snapshot())) == []

    def test_control_char_label_value_flagged(self):
        bad = 'pga_x{tenant="a\\nb"} 1\n'
        errors = M.lint_prometheus(bad)
        assert any("not prometheus-safe" in e.replace(
            "not prometheus-safe", "not prometheus-safe"
        ) for e in errors)

    def test_non_ascii_label_value_flagged(self):
        errors = M.lint_prometheus('pga_x{tenant="naïve"} 1\n')
        assert any("prometheus-safe" in e for e in errors)

    def test_overflow_label_value_flagged(self):
        errors = M.lint_prometheus('pga_x{tenant="_overflow"} 1\n')
        assert any("cardinality guard" in e for e in errors)

    def test_le_histogram_label_not_confused_with_overflow(self):
        r = M.MetricsRegistry()
        r.histogram("h.ms").observe(1.0)
        assert M.lint_prometheus(M.prometheus_text(r.snapshot())) == []

    def test_guarded_registry_exposition_is_flagged_end_to_end(self):
        r = M.MetricsRegistry()
        r.label_cardinality_limit = 1
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            r.counter("c", tenant="a").bump()
            r.counter("c", tenant="b").bump()
        errors = M.lint_prometheus(M.prometheus_text(r.snapshot()))
        assert any("cardinality guard" in e for e in errors)


# ------------------------------------------------------------ burn rate


class TestBurnRateMonitor:
    def _monitor(self, **kw):
        self.t = [0.0]
        kw.setdefault("budget", 0.1)
        kw.setdefault("fast_window_s", 10.0)
        kw.setdefault("slow_window_s", 40.0)
        kw.setdefault("threshold", 5.0)
        return M.BurnRateMonitor(clock=lambda: self.t[0], **kw)

    def test_burn_is_rate_over_budget(self):
        mon = self._monitor()
        for i in range(10):
            self.t[0] += 0.5
            mon.record("a", violated=(i % 2 == 0))
        b = mon.burn("a")
        assert b["fast_burn"] == pytest.approx(0.5 / 0.1)
        assert b["fast_samples"] == 10

    def test_alert_needs_both_windows(self):
        mon = self._monitor()
        # Violations confined to the distant past: outside the fast
        # window but inside the slow one — no alert (sustained-and-
        # current is what the two windows encode).
        for _ in range(10):
            self.t[0] += 1.0
            mon.record("a", True)
        self.t[0] += 25.0  # past the fast window, within the slow one
        mon.record("a", False)
        b = mon.burn("a")
        assert b["fast_burn"] == 0.0 and b["slow_burn"] > 5.0
        assert mon.check() == []

    def test_alert_transition_edge_and_rearm(self):
        mon = self._monitor()
        for _ in range(6):
            self.t[0] += 1.0
            mon.record("a", True)
        alerts = mon.check()
        assert [a["tenant"] for a in alerts] == ["a"]
        assert mon.check() == []  # still hot: no re-alert
        self.t[0] += 100.0  # everything ages out of both windows
        mon.record("a", False)
        assert mon.check() == []  # recovered: re-armed
        for _ in range(6):
            self.t[0] += 1.0
            mon.record("a", True)
        assert len(mon.check()) == 1  # fresh excursion alerts again

    def test_min_samples_gate(self):
        mon = self._monitor(min_samples=5)
        for _ in range(4):
            self.t[0] += 1.0
            mon.record("a", True)
        assert mon.check() == []  # burning, but under min_samples
        self.t[0] += 1.0
        mon.record("a", True)
        assert len(mon.check()) == 1

    def test_tenants_isolated(self):
        mon = self._monitor()
        for _ in range(6):
            self.t[0] += 1.0
            mon.record("hot", True)
            mon.record("cold", False)
        assert [a["tenant"] for a in mon.check()] == ["hot"]
        assert not mon.alerting("cold")


class TestSLOConfigTenants:
    def test_for_tenant_resolves_override(self):
        base = SLOConfig(
            p99_latency_ms=100.0,
            tenants={"vip": SLOConfig(p99_latency_ms=10.0)},
        )
        assert base.for_tenant("vip").p99_latency_ms == 10.0
        assert base.for_tenant("other") is base
        assert base.for_tenant(None) is base

    def test_override_inherits_base_burn(self):
        burn = BurnRateConfig(objective_ms=50.0)
        base = SLOConfig(
            burn=burn, tenants={"vip": SLOConfig(p99_latency_ms=10.0)}
        )
        assert base.for_tenant("vip").burn is burn
        own = BurnRateConfig(objective_ms=5.0)
        base2 = SLOConfig(
            burn=burn, tenants={"vip": SLOConfig(burn=own)}
        )
        assert base2.for_tenant("vip").burn is own

    def test_nested_overrides_rejected(self):
        inner = SLOConfig(tenants={"x": SLOConfig()})
        with pytest.raises(ValueError, match="nest"):
            SLOConfig(tenants={"vip": inner})

    def test_burn_config_validation(self):
        with pytest.raises(ValueError):
            BurnRateConfig(budget=0.0)
        with pytest.raises(ValueError):
            BurnRateConfig(fast_window_s=100.0, slow_window_s=10.0)
        with pytest.raises(ValueError):
            BurnRateConfig(threshold=0.0)


# --------------------------------------------------- serving queue path


@pytest.fixture
def queue_env():
    from libpga_tpu.config import ServingConfig
    from libpga_tpu.serving.batch import BatchedRuns
    from libpga_tpu.serving.queue import RunQueue

    registry = M.MetricsRegistry()
    ex = BatchedRuns("onemax", config=CFG)
    q = RunQueue(
        ex, serving=ServingConfig(max_batch=4, max_wait_ms=0),
        registry=registry,
    )
    yield q, registry
    q.close()


class TestQueueTenancy:
    def _req(self, seed=0):
        from libpga_tpu.serving.batch import RunRequest

        return RunRequest(size=128, genome_len=8, n=2, seed=seed)

    def test_ticket_carries_validated_tenant(self, queue_env):
        q, _ = queue_env
        t = q.submit(self._req(), tenant="team-a")
        anon = q.submit(self._req(1))
        q.drain()
        t.result(timeout=300)
        anon.result(timeout=300)
        assert t.tenant == "team-a" and t.timing.tenant == "team-a"
        assert anon.tenant == ANON and anon.timing.tenant == ANON
        # latency() stays the pure breakdown (round-11 contract).
        assert "tenant" not in t.latency()

    def test_invalid_tenant_rejected_at_submit(self, queue_env):
        q, _ = queue_env
        with pytest.raises(ValueError, match="invalid tenant"):
            q.submit(self._req(), tenant="bad tenant!")
        assert q.pending == 0  # nothing leaked into backpressure

    def test_per_tenant_series_and_gauges(self, queue_env):
        q, registry = queue_env
        for seed, tenant in enumerate(["a", "a", "b"]):
            q.submit(self._req(seed), tenant=tenant)
        q.drain()
        snap = registry.snapshot()
        counters = {
            (c["name"], c["labels"].get("tenant")): c["value"]
            for c in snap["counters"]
        }
        assert counters[("serving.tenant.submissions", "a")] == 2
        assert counters[("serving.tenant.submissions", "b")] == 1
        gauges = {
            (g["name"], g["labels"].get("tenant")): g["value"]
            for g in snap["gauges"]
        }
        assert ("serving.tenant.pending", "a") in gauges

    def test_completion_histograms_and_events_labeled(self, queue_env):
        q, registry = queue_env
        ticket = q.submit(self._req(), tenant="team-a")
        q.drain()
        ticket.result(timeout=300)
        snap = registry.snapshot()
        hists = {
            (h["name"], h["labels"].get("tenant")): h["count"]
            for h in snap["histograms"]
        }
        assert hists[("serving.tenant.e2e_ms", "team-a")] == 1
        assert hists[("serving.tenant.queue_wait_ms", "team-a")] == 1
        done = [
            r for r in T.FLIGHT.records() if r["event"] == "ticket_done"
        ]
        assert done and done[-1]["tenant"] == "team-a"

    def test_tenant_admit_emitted_once(self, queue_env):
        q, _ = queue_env
        T.FLIGHT.clear()
        q.submit(self._req(0), tenant="once")
        q.submit(self._req(1), tenant="once")
        q.drain()
        admits = [
            r for r in T.FLIGHT.records()
            if r["event"] == "tenant_admit" and r["tenant"] == "once"
        ]
        assert len(admits) == 1 and admits[0]["where"] == "serving_queue"

    def test_dead_letter_attributed(self, queue_env):
        from libpga_tpu.serving.batch import RunRequest

        q, registry = queue_env
        bad = q.submit(
            RunRequest(size=128, genome_len=8, n=2, seed=9,
                       genomes=np.zeros((3, 3), np.float32)),
            tenant="clumsy",
        )
        q.drain()
        with pytest.raises(ValueError):
            bad.result(timeout=300)
        snap = registry.snapshot()
        counters = {
            (c["name"], c["labels"].get("tenant")): c["value"]
            for c in snap["counters"]
        }
        assert counters[("serving.tenant.dead_letters", "clumsy")] == 1

    def test_tenant_burn_and_check_slo(self):
        from libpga_tpu.config import ServingConfig
        from libpga_tpu.serving.batch import BatchedRuns
        from libpga_tpu.serving.queue import RunQueue

        registry = M.MetricsRegistry()
        burn = BurnRateConfig(
            objective_ms=1e-4, budget=0.5, fast_window_s=30,
            slow_window_s=60, threshold=1.5, min_samples=1,
        )
        slo = SLOConfig(tenants={"slow": SLOConfig(burn=burn)})
        q = RunQueue(
            BatchedRuns("onemax", config=CFG),
            serving=ServingConfig(max_batch=4, max_wait_ms=0),
            slo=slo, registry=registry,
        )
        try:
            t1 = q.submit(self._req(0), tenant="slow")
            t2 = q.submit(self._req(1), tenant="fast")
            q.drain()
            t1.result(timeout=300)
            t2.result(timeout=300)
            violations = q.check_slo(tenant="slow")
            assert any(
                v["what"] == "tenant_burn_rate" for v in violations
            )
            assert q.check_slo(tenant="fast") == []
            gauges = {
                (g["labels"].get("tenant"), g["labels"].get("window"))
                for g in registry.snapshot()["gauges"]
                if g["name"] == "serving.tenant.slo_burn"
            }
            assert ("slow", "fast") in gauges and ("slow", "slow") in gauges
        finally:
            q.close()


# ------------------------------------------- spool flush / merge (fleet)


class TestTenantSnapshotMerge:
    def _snap(self, tenants):
        r = M.MetricsRegistry()
        for tenant, values in tenants.items():
            for v in values:
                r.histogram(
                    "serving.tenant.e2e_ms", tenant=tenant
                ).observe(v)
            r.counter(
                "serving.tenant.completions", tenant=tenant
            ).bump(len(values))
        return r.snapshot()

    def test_labels_preserved_through_spool_flush_merge(self, tmp_path):
        from libpga_tpu.serving.fleet import (
            Spool, merge_spool_metrics, write_metrics_file,
        )

        spool = Spool(str(tmp_path / "spool"))
        write_metrics_file(
            spool, "w0", self._snap({"a": [1.0, 2.0], "b": [5.0]})
        )
        write_metrics_file(
            spool, "w1", self._snap({"a": [3.0]})
        )
        merged = merge_spool_metrics(spool)
        # Per-proc labeled series keep their tenant label...
        labeled = {
            (h["labels"].get("proc"), h["labels"].get("tenant")): h
            for h in merged["histograms"]
            if h["name"] == "serving.tenant.e2e_ms"
            and "proc" in h["labels"]
        }
        assert labeled[("w0", "a")]["count"] == 2
        assert labeled[("w1", "a")]["count"] == 1
        # ...and the proc-free aggregates fold PER TENANT.
        agg = {
            h["labels"]["tenant"]: h for h in merged["histograms"]
            if h["name"] == "serving.tenant.e2e_ms"
            and "proc" not in h["labels"]
        }
        assert agg["a"]["count"] == 3 and agg["b"]["count"] == 1
        counters = {
            (c["labels"].get("proc"), c["labels"].get("tenant")):
                c["value"]
            for c in merged["counters"]
            if c["name"] == "serving.tenant.completions"
        }
        assert counters[("w0", "b")] == 1

    def test_mixed_tenant_merge_associative(self):
        """Folding three mixed-tenant process snapshots in one call
        equals folding the first pair's per-tenant aggregates with the
        third via ``HistogramSnapshot.merge`` — per tenant."""
        s1 = self._snap({"a": [1.0, 10.0]})
        s2 = self._snap({"a": [100.0], "b": [2.0]})
        s3 = self._snap({"a": [7.0], "b": [4.0, 8.0]})

        def agg_of(merged):
            return {
                h["labels"]["tenant"]: M.HistogramSnapshot.from_dict(h)
                for h in merged["histograms"]
                if h["name"] == "serving.tenant.e2e_ms"
                and "proc" not in h["labels"]
            }

        all_three = agg_of(
            M.merge_snapshots([("p1", s1), ("p2", s2), ("p3", s3)])
        )
        pair = agg_of(M.merge_snapshots([("p1", s1), ("p2", s2)]))
        third = agg_of(M.merge_snapshots([("p3", s3)]))
        for tenant in ("a", "b"):
            refolded = pair[tenant].merge(third[tenant]) if (
                tenant in pair
            ) else third[tenant]
            assert all_three[tenant].counts == refolded.counts
            assert all_three[tenant].sum == refolded.sum
        assert all_three["a"].count == 4
        assert all_three["b"].count == 3

    def test_schema_version_refusal_still_applies(self):
        s1 = self._snap({"a": [1.0]})
        s2 = dict(self._snap({"b": [1.0]}), schema=99)
        with pytest.raises(ValueError, match="refusing to merge"):
            M.merge_snapshots([("p1", s1), ("p2", s2)])


# ------------------------------------------------------ fleet ticket ids


class TestFleetTicketTenant:
    def test_ticket_normalizes_and_validates(self):
        from libpga_tpu.serving.fleet import FleetTicket

        t = FleetTicket(size=64, genome_len=8, n=1, seed=0)
        assert t.tenant == ANON
        t2 = FleetTicket(size=64, genome_len=8, n=1, seed=0,
                         tenant="team-a")
        assert t2.tenant == "team-a"
        import dataclasses

        assert dataclasses.asdict(t2)["tenant"] == "team-a"
        with pytest.raises(ValueError, match="invalid tenant"):
            FleetTicket(size=64, genome_len=8, n=1, seed=0,
                        tenant="no way")


# --------------------------------------------- session lifecycle tracing


class TestSessionLifecycleTrace:
    def test_spans_telescope_and_validate(self):
        from libpga_tpu.streaming import EvolutionSession

        s = EvolutionSession(
            "onemax", 128, 8, seed=3, config=CFG, tenant="team-a"
        )
        s.ask(2)
        s.tell(np.zeros((1, 8), np.float32), np.array([1.0], np.float32))
        s.step(2)
        spans = s.trace()
        assert [r["span"] for r in spans] == ["open", "ask", "tell", "step"]
        for rec in spans:
            T.validate_event(rec)
            assert rec["event"] == "session_span"
            assert rec["tenant"] == "team-a"
            assert rec["session"] == s.sid
        # Telescoping: each span starts where the previous ended.
        for prev, cur in zip(spans, spans[1:]):
            assert cur["t0"] == prev["t1"]
        assert s.trace_coverage() >= 0.95

    def test_trace_survives_suspend_resume(self, tmp_path):
        from libpga_tpu.streaming import EvolutionSession

        s = EvolutionSession(
            "onemax", 128, 8, seed=3, config=CFG, tenant="team-a"
        )
        s.step(1)
        path = str(tmp_path / "sess.npz")
        s.suspend(path)
        assert os.path.exists(f"{path}.trace.jsonl")
        back = EvolutionSession.resume(path, config=CFG)
        assert back.tenant == "team-a"
        back.step(1)
        spans = [r["span"] for r in back.trace()]
        assert spans == ["open", "step", "suspend", "resume", "step"]
        assert back.trace_coverage() >= 0.95
        for prev, cur in zip(back.trace(), back.trace()[1:]):
            assert cur["t0"] == prev["t1"]

    def test_group_step_keeps_each_sessions_trace(self):
        from libpga_tpu.streaming import EvolutionSession, SessionGroup

        sessions = [
            EvolutionSession(
                "onemax", 128, 8, seed=i, config=CFG,
                tenant=f"g{i}",
            )
            for i in range(2)
        ]
        group = SessionGroup(sessions, tell_slots=2)
        group.step(2)
        for s in sessions:
            assert [r["span"] for r in s.trace()] == ["open", "group_step"]
            assert s.trace()[-1]["tenant"] == s.tenant

    def test_store_discard_removes_trace_sidecar(self, tmp_path):
        from libpga_tpu.streaming import EvolutionSession
        from libpga_tpu.streaming.store import SessionStore

        store = SessionStore(str(tmp_path / "store"))
        s = EvolutionSession("onemax", 128, 8, seed=1, config=CFG)
        store.suspend(s)
        trace_path = f"{store.path(s.sid)}.trace.jsonl"
        assert os.path.exists(trace_path)
        store.discard(s.sid)
        assert not os.path.exists(trace_path)

    def test_suspend_meta_carries_tenant(self, tmp_path):
        from libpga_tpu.streaming import EvolutionSession

        s = EvolutionSession(
            "onemax", 128, 8, seed=1, config=CFG, tenant="kept"
        )
        path = str(tmp_path / "m.npz")
        s.suspend(path)
        with open(f"{path}.session.json") as fh:
            assert json.load(fh)["tenant"] == "kept"


# --------------------------------------------- host-side-only acceptance


class TestAttributionIsHostSideOnly:
    def test_mega_run_stablehlo_byte_identical(self):
        """The compiled serving program cannot see the tenant: the
        canonical StableHLO digest of the bucket's mega-run is one and
        the same whether the executor serves attributed or anonymous
        traffic (there is nothing tenant-shaped to bake in — pinned
        here so a future 'optimization' cannot quietly change that)."""
        import dataclasses as _dc

        import jax
        import jax.numpy as jnp

        from libpga_tpu.analysis import fingerprint
        from libpga_tpu.config import ServingConfig
        from libpga_tpu.serving.batch import BatchedRuns

        shapes = (
            jax.ShapeDtypeStruct((2, 64, 8), jnp.float32),
            jax.ShapeDtypeStruct((2, 2), jnp.uint32),
            jax.ShapeDtypeStruct((2,), jnp.int32),
            jax.ShapeDtypeStruct((2,), jnp.float32),
            jax.ShapeDtypeStruct((2, 1, 2), jnp.float32),
        )
        serving = _dc.replace(ServingConfig(), aot_warmup=False)

        def build():
            ex = BatchedRuns("onemax", config=CFG, serving=serving)
            return ex._build_mega(2, 64, 8, "run_major")

        fp = [fingerprint(build(), *shapes) for _ in range(2)]
        assert fp[0] == fp[1]

    def test_two_tenants_share_one_compiled_program(self):
        from libpga_tpu.config import ServingConfig
        from libpga_tpu.serving import COUNTERS
        from libpga_tpu.serving.batch import BatchedRuns, RunRequest
        from libpga_tpu.serving.queue import RunQueue

        ex = BatchedRuns("onemax", config=CFG)
        before = COUNTERS.snapshot().get("builds", 0)
        q = RunQueue(
            ex, serving=ServingConfig(max_batch=2, max_wait_ms=0),
            registry=M.MetricsRegistry(),
        )
        try:
            ta = q.submit(
                RunRequest(size=96, genome_len=8, n=2, seed=1),
                tenant="a",
            )
            tb = q.submit(
                RunRequest(size=96, genome_len=8, n=2, seed=2),
                tenant="b",
            )
            q.drain()
            ra = np.asarray(ta.result(timeout=300).genomes)
            tb.result(timeout=300)
        finally:
            q.close()
        assert COUNTERS.snapshot().get("builds", 0) - before == 1
        # And the attributed result is bit-identical to the anonymous
        # one: attribution cannot touch the math.
        q2 = RunQueue(
            BatchedRuns("onemax", config=CFG),
            serving=ServingConfig(max_batch=2, max_wait_ms=0),
            registry=M.MetricsRegistry(),
        )
        try:
            t_anon = q2.submit(RunRequest(size=96, genome_len=8, n=2,
                                          seed=1))
            q2.drain()
            r_anon = np.asarray(t_anon.result(timeout=300).genomes)
        finally:
            q2.close()
        assert np.array_equal(ra, r_anon)
