"""Elastic-fleet scheduling layer (ISSUE 15): weighted-fair
deficit-round-robin, admission control, priority preemption, and the
load-following autoscaler.

The acceptance invariants pinned here:

- DRR never starves a nonempty tenant queue (property test over random
  arrival patterns), including tenants whose shapes never co-batch;
- per-tenant quotas shed DETERMINISTICALLY under concurrent submitters
  (exactly ``max_pending`` admitted, whatever the interleaving);
- the autoscaler's hysteresis produces zero decisions under oscillating
  load and follows sustained load up and back down to the floor;
- a preempted supervised batch resumes BIT-IDENTICAL (the round-13
  chunk-boundary drain discipline) while the high-priority arrival
  takes the slot;
- an autoscaled fleet's results are bit-identical to a fixed-size
  fleet's on the same seeds.

Process-spawning tests keep shapes tiny (tier-1 budget); the end-to-end
burst-vs-steady SLO isolation smoke is ``tools/fairness_smoke.py``
(CI stage 16).
"""

import json
import os
import random
import threading
import time

import numpy as np
import pytest

from libpga_tpu import PGA, PGAConfig
from libpga_tpu.config import AutoscaleConfig, FleetConfig, TenantPolicy
from libpga_tpu.robustness.supervisor import supervised_run
from libpga_tpu.serving.fleet import Fleet, FleetTicket, Spool
from libpga_tpu.serving.scheduler import (
    Autoscaler,
    DirWatch,
    FleetScheduler,
    QuotaExceeded,
    SchedEntry,
    release_room,
)
from libpga_tpu.utils import metrics as _metrics
from libpga_tpu.utils import telemetry

POP, LEN = 128, 16
CFG = PGAConfig(use_pallas=False)


def engine_run(seed, n, pop=POP, length=LEN):
    pga = PGA(seed=seed, config=CFG)
    pga.create_population(pop, length)
    pga.set_objective("onemax")
    pga.run(n)
    return np.array(pga._populations[0].genomes, copy=True)


def wait_for(cond, timeout=60, interval=0.02, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


def mk_entry(i, tenant, bucket, prio=0, t=0.0):
    return SchedEntry(
        tid=f"t{i:04d}", ticket=None, bucket=bucket, tenant=tenant,
        priority=prio, admitted=t,
    )


def drain_all(sched, max_batch=4, urgent=True):
    """Draw until empty; returns the list of (priority, bucket,
    entries) draws."""
    draws = []
    guard = 0
    while sched.depth() > 0:
        nb = sched.next_batch(1e9, max_batch, 0.0, urgent=urgent)
        assert nb is not None, "due work but no batch drawn"
        draws.append(nb)
        guard += 1
        assert guard < 10_000
    return draws


# ------------------------------------------------------------ validation


def test_policy_and_config_validation():
    with pytest.raises(ValueError):
        TenantPolicy(weight=0.0)
    with pytest.raises(ValueError):
        TenantPolicy(weight=-1.0)
    with pytest.raises(ValueError):
        TenantPolicy(max_pending=0)
    with pytest.raises(ValueError):
        TenantPolicy(priority=10)
    with pytest.raises(ValueError):
        AutoscaleConfig(min_workers=4, max_workers=2)
    with pytest.raises(ValueError):
        AutoscaleConfig(target_backlog=0.0)
    with pytest.raises(ValueError):
        AutoscaleConfig(step=0)
    with pytest.raises(ValueError):
        AutoscaleConfig(check_s=0.0)
    with pytest.raises(ValueError):
        FleetConfig(tenants={"a": object()})
    with pytest.raises(ValueError):
        FleetConfig(sched_quantum=0.0)
    with pytest.raises(ValueError):
        FleetConfig(sched_lookahead=0)
    with pytest.raises(ValueError):
        FleetConfig(poll_s=0.5, poll_idle_max_s=0.1)
    with pytest.raises(ValueError):
        FleetTicket(size=8, genome_len=8, n=1, seed=0, priority=10)
    # Valid shapes construct.
    FleetConfig(
        tenants={"a": TenantPolicy(weight=2.0, max_pending=4, priority=3)},
        autoscale=AutoscaleConfig(),
    )
    FleetTicket(size=8, genome_len=8, n=1, seed=0, priority=9)


# ------------------------------------------------------------------- DRR


def test_drr_single_tenant_preserves_fifo():
    sched = FleetScheduler(FleetConfig())
    B = (128, 16, False)
    for i in range(7):
        sched.push(mk_entry(i, "anon", B))
    draws = drain_all(sched, max_batch=3)
    order = [e.tid for _, _, es in draws for e in es]
    assert order == [f"t{i:04d}" for i in range(7)]
    # Batches are homogeneous in bucket and bounded by max_batch.
    assert [len(es) for _, _, es in draws] == [3, 3, 1]


def test_drr_burst_cannot_starve_steady():
    """A burst tenant's 50-deep queue of shape X cannot delay a steady
    tenant's shape-Y ticket beyond its deficit quantum: the steady
    ticket rides the very next draw after the burst's current batch."""
    sched = FleetScheduler(FleetConfig())
    X, Y = (1024, 64, False), (128, 16, False)
    for i in range(50):
        sched.push(mk_entry(i, "burst", X))
    # Steady arrives AFTER the whole burst is queued.
    sched.push(mk_entry(99, "steady", Y))
    first = sched.next_batch(1e9, 8, 0.0, urgent=True)
    second = sched.next_batch(1e9, 8, 0.0, urgent=True)
    tenants = [es[0].tenant for _, _, es in (first, second)]
    assert "steady" in tenants, tenants


def test_drr_no_starvation_random_arrivals():
    """Property test: over random tenants/weights/shapes/interleavings,
    every queued ticket is eventually drawn, and while every tenant
    stays backlogged no tenant waits more than one full ring rotation
    (+1 slack for debt paydown) between its batches."""
    for seed in range(5):
        rng = random.Random(seed)
        n_tenants = rng.randint(2, 5)
        tenants = [f"ten{j}" for j in range(n_tenants)]
        policies = {
            t: TenantPolicy(weight=rng.choice((0.5, 1.0, 2.0)))
            for t in tenants
        }
        # Half the runs give every tenant a PRIVATE shape (never
        # co-batches), half share one shape pool.
        disjoint = rng.random() < 0.5
        shapes = {
            t: ((64 * (j + 1), 16, False) if disjoint
                else (64 * rng.randint(1, 2), 16, False))
            for j, t in enumerate(tenants)
        }
        sched = FleetScheduler(
            FleetConfig(tenants=policies), policies=policies
        )
        pushed = 0
        for i in range(rng.randint(40, 120)):
            t = rng.choice(tenants)
            sched.push(mk_entry(i, t, shapes[t]))
            pushed += 1
        max_batch = rng.choice((1, 2, 4))
        backlogged = {
            t: n for t, n in sched.tenant_depth().items()
        }
        last_served = {t: 0 for t in backlogged}
        draw_i = 0
        drawn = 0
        while sched.depth() > 0:
            nb = sched.next_batch(1e9, max_batch, 0.0, urgent=True)
            assert nb is not None
            draw_i += 1
            _, bucket, entries = nb
            assert all(e.bucket == bucket for e in entries)
            drawn += len(entries)
            served = {e.tenant for e in entries}
            depth = sched.tenant_depth()
            for t in served:
                last_served[t] = draw_i
            # Starvation bound, checked over tenants still backlogged:
            # the gap since their last batch is bounded by the ring
            # size plus debt-paydown slack (max_batch/weight rotations
            # compressed into draws).
            for t, n in depth.items():
                if n > 0:
                    gap = draw_i - last_served.get(t, 0)
                    bound = len(depth) * (
                        1 + max_batch / policies[t].weight
                    ) + 2
                    assert gap <= bound, (
                        f"seed {seed}: tenant {t} gap {gap} > {bound}"
                    )
        assert drawn == pushed


def test_drr_weighted_share():
    """Under saturation with a shared shape, drawn tickets split
    approximately by weight (3:1 here)."""
    policies = {
        "heavy": TenantPolicy(weight=3.0), "light": TenantPolicy(),
    }
    sched = FleetScheduler(policies=policies)
    B = (128, 16, False)
    for i in range(120):
        sched.push(mk_entry(i, "heavy", B))
        sched.push(mk_entry(1000 + i, "light", B))
    counts = {"heavy": 0, "light": 0}
    for _ in range(24):  # leave both queues nonempty throughout
        nb = sched.next_batch(1e9, 4, 0.0, urgent=True)
        for e in nb[2]:
            counts[e.tenant] += 1
    ratio = counts["heavy"] / max(counts["light"], 1)
    assert 2.0 <= ratio <= 4.5, counts


def test_drr_priority_lanes_strict():
    """Higher lanes drain before lower ones, and batch names encode
    the lane so the workers' name-sorted claim serves it first."""
    sched = FleetScheduler(FleetConfig())
    B = (128, 16, False)
    sched.push(mk_entry(0, "low", B, prio=0))
    sched.push(mk_entry(1, "high", B, prio=9))
    sched.push(mk_entry(2, "mid", B, prio=4))
    prios = [
        sched.next_batch(1e9, 1, 0.0, urgent=True)[0] for _ in range(3)
    ]
    assert prios == [9, 4, 0]
    assert Spool.name_priority("p0b00001-x-128x16.json") == 9
    assert Spool.name_priority("p9b00002-x-128x16-sup.json") == 0
    assert Spool.name_priority("b00003-x-128x16.json") == 0  # legacy


def test_release_room_window():
    """The release-window headroom formula (ISSUE 18): lookahead per
    live worker minus spooled-but-unclaimed, with a one-worker floor
    (a worker-less fleet still spools work for late arrivals) and
    negative spool counts clamped (a torn ring depth must never
    widen the window)."""
    assert release_room(2, 3, 0) == 6
    assert release_room(2, 3, 4) == 2
    assert release_room(2, 3, 7) == -1  # over-released: hold back
    assert release_room(2, 0, 0) == 2  # worker-less floor
    assert release_room(2, 0, 2) == 0
    assert release_room(2, 3, -5) == 6  # bad depth estimate clamps


def test_admission_window_not_urgent():
    """Below max_batch and inside max_wait_ms nothing is due; aging
    past the window makes it due without urgency."""
    sched = FleetScheduler(FleetConfig())
    B = (128, 16, False)
    sched.push(mk_entry(0, "anon", B, t=100.0))
    assert sched.next_batch(100.01, 8, 1000.0, urgent=False) is None
    nb = sched.next_batch(101.5, 8, 1000.0, urgent=False)
    assert nb is not None and len(nb[2]) == 1


# ------------------------------------------------------ quota determinism


def test_quota_deterministic_under_concurrent_submitters(tmp_path):
    """N threads race a quota of 3: exactly 3 tickets admit, every
    other submit raises QuotaExceeded, and each shed emits one
    schema-valid quota_reject event."""
    events_path = str(tmp_path / "events.jsonl")
    log = telemetry.EventLog(events_path)
    reg = _metrics.MetricsRegistry()
    fleet = Fleet(
        str(tmp_path / "spool"), "onemax", config=CFG,
        fleet=FleetConfig(
            n_workers=1, max_wait_ms=10_000,
            tenants={"q": TenantPolicy(max_pending=3)},
        ),
        events=log, registry=reg,
    )
    admitted, rejected = [], []
    barrier = threading.Barrier(4)

    def submitter():
        barrier.wait()
        for i in range(5):
            try:
                admitted.append(fleet.submit(FleetTicket(
                    size=POP, genome_len=LEN, n=1, seed=i, tenant="q",
                )))
            except QuotaExceeded:
                rejected.append(i)

    threads = [threading.Thread(target=submitter) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(admitted) == 3
    assert len(rejected) == 17
    # Unquota'd tenants are untouched by the shed.
    fleet.submit(FleetTicket(size=POP, genome_len=LEN, n=1, seed=9))
    fleet.close()
    log.close()
    records = telemetry.validate_log(events_path)
    rejects = [r for r in records if r["event"] == "quota_reject"]
    assert len(rejects) == 17
    assert all(r["tenant"] == "q" and r["limit"] == 3 for r in rejects)
    snap = reg.snapshot()
    cnt = [
        c for c in snap["counters"]
        if c["name"] == "fleet.sched.quota_rejects"
    ]
    assert cnt and cnt[0]["value"] == 17


# ------------------------------------------------------------- autoscaler


def test_autoscaler_hysteresis_no_flap():
    """Load oscillating between idle and just-under the up threshold
    produces ZERO decisions; sustained load scales up at cooldown
    cadence; sustained idleness drains to the floor."""
    cfg = AutoscaleConfig(
        min_workers=1, max_workers=4, target_backlog=2.0,
        up_cooldown_s=1.0, down_cooldown_s=1.0, idle_grace_s=2.0,
    )
    sc = Autoscaler(cfg)
    now = 0.0
    alive = 2
    for i in range(200):  # 20 simulated seconds of oscillation
        now += 0.1
        backlog = 3 if i % 2 == 0 else 0  # below 2.0 * 2 when busy
        delta, _ = sc.decide(now, alive, backlog, claimed=0)
        assert delta == 0, f"flapped at t={now}: {delta}"
    # Sustained overload: one step up per cooldown, to the max.
    ups = []
    for _ in range(60):
        now += 0.1
        delta, reason = sc.decide(now, alive, backlog=100, claimed=1)
        if delta > 0:
            assert reason == "backlog"
            alive += delta
            ups.append(now)
    assert alive == 4
    assert all(b - a >= cfg.up_cooldown_s - 1e-9
               for a, b in zip(ups, ups[1:]))
    # Sustained idleness: grace first, then one step down per cooldown.
    downs = []
    idle_start = now
    for _ in range(100):
        now += 0.1
        delta, reason = sc.decide(now, alive, backlog=0, claimed=0)
        if delta < 0:
            assert reason == "idle"
            alive += delta
            downs.append(now)
    assert alive == cfg.min_workers
    assert downs[0] - idle_start >= cfg.idle_grace_s - 1e-9
    # A single busy blip re-arms the idle grace clock.
    delta, _ = sc.decide(now + 0.1, alive + 1, backlog=1, claimed=0)
    assert delta == 0
    delta, _ = sc.decide(now + 0.2, alive + 1, backlog=0, claimed=0)
    assert delta == 0  # grace restarted, no instant retire


def test_autoscaler_floor_and_signal_triggers():
    cfg = AutoscaleConfig(
        min_workers=2, max_workers=4, target_backlog=10.0,
        spool_wait_p99_ms=50.0, up_cooldown_s=0.0,
    )
    sc = Autoscaler(cfg)
    # Below the floor: restored regardless of load or cooldown.
    assert sc.decide(1.0, 0, 0, 0) == (2, "floor")
    # Latency trigger fires only while busy.
    assert sc.decide(2.0, 2, 0, 0, spool_wait_p99=500.0)[0] == 0
    delta, reason = sc.decide(3.0, 2, 1, 0, spool_wait_p99=500.0)
    assert (delta, reason) == (1, "spool_wait")
    # Burn-rate trigger.
    delta, reason = sc.decide(4.0, 2, 1, 0, burn_alerts=1)
    assert (delta, reason) == (1, "slo_burn")
    # Straggler supplement needs waiting work.
    assert sc.decide(5.0, 2, 0, 1, stragglers=1)[0] == 0
    delta, reason = sc.decide(6.0, 2, 1, 1, stragglers=1)
    assert (delta, reason) == (1, "straggler")


# --------------------------------------------- incremental scan / backoff


def test_dirwatch_detects_entry_changes(tmp_path):
    d = tmp_path / "watched"
    d.mkdir()
    w = DirWatch(str(d))
    assert w.poll() is True  # no baseline yet
    assert w.poll() is False
    (d / "a.json").write_text("{}")
    assert w.poll() is True
    assert w.poll() is False
    os.remove(d / "a.json")
    assert w.poll() is True


def test_monitor_idle_backoff_and_scan_metric(tmp_path):
    reg = _metrics.MetricsRegistry()
    fleet = Fleet(
        str(tmp_path), "onemax", config=CFG,
        fleet=FleetConfig(
            n_workers=1, poll_s=0.01, poll_idle_max_s=0.32,
            max_wait_ms=10_000,
        ),
        registry=reg,
    )
    fleet._ensure_monitor()
    wait_for(
        lambda: fleet._wait_s >= 0.16, timeout=30,
        what="idle poll backoff growth",
    )
    assert reg.histogram("fleet.coordinator.scan_ms").snapshot().count > 0
    # A submission snaps the cadence back to poll_s (outstanding work
    # keeps the monitor active).
    fleet.submit(FleetTicket(size=POP, genome_len=LEN, n=1, seed=1))
    wait_for(
        lambda: fleet._wait_s == fleet.fleet.poll_s, timeout=30,
        what="backoff reset on submit",
    )
    fleet.close()


def test_release_window_holds_backlog(tmp_path):
    """With no live workers the coordinator spools at most
    sched_lookahead batches and holds the rest in its fair queues;
    flush() overrides the window."""
    fleet = Fleet(
        str(tmp_path), "onemax", config=CFG,
        fleet=FleetConfig(
            n_workers=1, max_batch=1, max_wait_ms=10_000,
            sched_lookahead=2,
        ),
    )
    for i in range(10):
        fleet.submit(FleetTicket(size=POP, genome_len=LEN, n=1, seed=i))
    assert len(fleet.spool.pending_batches()) == 2
    assert fleet.sched.depth() == 8
    assert fleet.flush() == 8
    assert len(fleet.spool.pending_batches()) == 10
    assert fleet.sched.depth() == 0
    # Priority rides the names: a high-priority submit sorts first.
    fleet.submit(FleetTicket(
        size=POP, genome_len=LEN, n=1, seed=99, priority=9,
    ))
    fleet.flush()
    names = fleet.spool.pending_batches()
    assert Spool.name_priority(names[0]) == 9
    batch = Spool.read_json(fleet.spool.path("pending", names[0]))
    assert batch["priority"] == 9
    assert batch["tickets"][0]["seed"] == 99
    fleet.close()


# -------------------------------------------------------- with processes


def test_preemption_resume_bit_identity(tmp_path):
    """ACCEPTANCE: a high-priority arrival preempts the single worker's
    low-priority supervised batch at a chunk boundary (marker, not
    SIGTERM — the process survives), takes the slot, and the preempted
    run resumes BIT-IDENTICAL to an uninterrupted same-seed supervised
    run at the same cadence."""
    N, K, SUP_POP = 24, 1, 2048
    events_path = str(tmp_path / "events.jsonl")
    log = telemetry.EventLog(events_path)
    reg = _metrics.MetricsRegistry()
    fleet = Fleet(
        str(tmp_path / "spool"), "onemax", config=CFG,
        fleet=FleetConfig(
            n_workers=1, max_batch=1, max_wait_ms=0,
            lease_timeout_s=30.0, heartbeat_s=0.5, poll_s=0.02,
        ),
        events=log, registry=reg,
    )
    try:
        fleet.start()
        h_low = fleet.submit(FleetTicket(
            size=SUP_POP, genome_len=LEN, n=N, seed=9,
            checkpoint_every=K, priority=0,
        ))
        fleet.flush()
        sidecar = fleet.spool.ckpt_path(h_low.tid) + ".meta.json"

        def mid_run():
            try:
                with open(sidecar) as fh:
                    return 0 < json.load(fh)["generations"] < N
            except (OSError, json.JSONDecodeError, KeyError):
                return False

        wait_for(mid_run, timeout=120, interval=0.002,
                 what="first durable checkpoint")
        h_high = fleet.submit(FleetTicket(
            size=POP, genome_len=LEN, n=4, seed=4, priority=9,
        ))
        wait_for(
            lambda: fleet.registry.counter(
                "fleet.sched.preemptions"
            ).value > 0,
            timeout=120, what="preemption marker",
        )
        res_high = h_high.result(timeout=240)
        res_low = h_low.result(timeout=240)
    finally:
        fleet.close()
        log.close()
    # High-priority plain ticket: bit-identical to a standalone run.
    assert np.array_equal(res_high.genomes, engine_run(4, 4))
    # Preempted supervised ticket: bit-identical to an uninterrupted
    # same-seed supervised run at the same cadence.
    ref = PGA(seed=9, config=CFG)
    ref.create_population(SUP_POP, LEN)
    ref.set_objective("onemax")
    supervised_run(
        ref, N, checkpoint_path=str(tmp_path / "ref.npz"),
        checkpoint_every=K,
    )
    assert res_low.generations == N
    assert np.array_equal(
        res_low.genomes, np.array(ref._populations[0].genomes)
    )
    records = telemetry.validate_log(events_path)
    kinds = [r["event"] for r in records]
    assert "preempt" in kinds
    # The preempted batch's trace shows the preemption record.
    assert any(r.get("span") == "preempt" for r in res_low.trace or [])


def test_autoscaler_follows_load_bit_identical(tmp_path):
    """ACCEPTANCE: worker count rises under a submission burst and
    drains back to the floor within the cooldown window, with ZERO
    result-bit differences versus a fixed-size fleet on the same
    seeds (here: versus the standalone engine, the fixed fleet's own
    pinned reference)."""
    events_path = str(tmp_path / "events.jsonl")
    log = telemetry.EventLog(events_path)
    reg = _metrics.MetricsRegistry()
    fleet = Fleet(
        str(tmp_path / "spool"), "onemax", config=CFG,
        fleet=FleetConfig(
            n_workers=1, max_batch=1, max_wait_ms=5, poll_s=0.02,
            lease_timeout_s=60.0, heartbeat_s=0.5,
            autoscale=AutoscaleConfig(
                min_workers=1, max_workers=2, target_backlog=1.0,
                up_cooldown_s=0.2, down_cooldown_s=0.3,
                idle_grace_s=0.4, check_s=0.05,
            ),
        ),
        events=log, registry=reg,
    )
    try:
        fleet.start()
        seeds = (1, 2, 3, 4, 5, 6)
        handles = [
            fleet.submit(FleetTicket(
                size=POP, genome_len=LEN, n=4, seed=s,
            ))
            for s in seeds
        ]
        wait_for(
            lambda: len(fleet.workers_alive()) == 2, timeout=120,
            what="scale-up under burst",
        )
        results = [h.result(timeout=240) for h in handles]
        for seed, res in zip(seeds, results):
            assert np.array_equal(res.genomes, engine_run(seed, 4)), (
                f"seed {seed} diverged under autoscaling"
            )
        wait_for(
            lambda: len(fleet.workers_alive()) == 1, timeout=120,
            what="scale-down to the floor",
        )
        # The retirement was a DRAIN: the retired worker exited 0 (a
        # non-zero exit would have counted as a death).
        assert fleet.worker_deaths == 0
    finally:
        fleet.close()
        log.close()
    records = telemetry.validate_log(events_path)
    kinds = [r["event"] for r in records]
    assert "autoscale_up" in kinds
    assert "autoscale_down" in kinds
    ups = [r for r in records if r["event"] == "autoscale_up"]
    assert all(r["reason"] == "backlog" for r in ups)
