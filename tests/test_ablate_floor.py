"""CPU smoke tests for the floor-attribution harness (tools/ablate_floor.py).

The harness's TIMINGS are hardware quantities (it refuses to run off
TPU), but everything else is testable here: each ablation variant must
build and execute under interpret mode at a toy shape, the pure-copy
kernel must be EXACTLY the identity up to the output layout's riffle
permutation (that property is what makes its timing a clean
HBM+grid-machinery probe), and the partition arithmetic must sum to the
floor it decomposes.
"""

import importlib.util
import pathlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

_SPEC = importlib.util.spec_from_file_location(
    "ablate_floor",
    pathlib.Path(__file__).resolve().parent.parent / "tools" / "ablate_floor.py",
)
af = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(af)


def _interpret():
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.force_tpu_interpret_mode()


POP, L, K, D = 512, 16, 128, 2
DT = jnp.float32


def _build(name, **kw):
    return af.build_variant(
        name, DT, K, D, POP, L, interpret_ok=True, **kw
    )


def _inputs(breed):
    gp = jax.random.uniform(jax.random.key(1), (breed.Pp, breed.Lp))
    sp = jnp.sum(gp[:, :L], axis=1)
    return gp, sp


class TestVariantsRunAtToyShape:
    """Every harness variant builds and executes in interpret mode."""

    @pytest.mark.parametrize(
        "name,kw",
        [
            ("full", dict()),
            ("full_serial", dict(ablate=("serial_grid",))),
            ("full_nodonate", dict(donate=False)),
            ("floor", dict(ablate=af.FLOOR_ABLATE, fused=False)),
            ("copy_riffle_score", dict(ablate=af.COPY)),
            ("copy_riffle", dict(ablate=af.COPY, fused=False)),
            ("copy_contig", dict(ablate=af.COPY + ("no_riffle",), fused=False)),
        ],
    )
    def test_variant_runs(self, name, kw):
        with _interpret():
            run = _build(name, **kw)
            assert run is not None, name
            assert run.breed.K == K and run.breed.D == D
            run(2)

    def test_rank_sort_variant_runs(self):
        with _interpret():
            run = af.build_rank_sort(DT, K, D, POP, L)
            assert run is not None
            run(2)

    def test_pingpong_alias_variant_runs_and_alternates(self):
        """The shipped-lever variant (ISSUE 3): builds the ping-pong
        breed and its loop body alternates parity via lax.cond — two
        iterations exercise both aliased kernels."""
        with _interpret():
            run = _build("pingpong_alias", layout="pingpong")
            assert run is not None
            assert run.breed.layout == "pingpong"
            assert run.breed.parities == 2
            run(2)

    def test_subblock_variant_runs_with_reduced_grid(self):
        with _interpret():
            base = _build("pingpong_alias", layout="pingpong")
            run = _build("subblock", layout="pingpong", subblock=2)
            assert run is not None
            assert run.breed.subblock == 2
            assert run.breed.grid_steps * 2 == base.breed.grid_steps
            run(2)

    def test_unknown_ablate_flag_raises_naming_valid_set(self):
        """Satellite (ISSUE 3): a typo'd flag must raise instead of
        silently measuring the full kernel."""
        import pytest

        with pytest.raises(ValueError) as ei:
            _build("typo", ablate=("no_rifle",), fused=False)
        assert "no_rifle" in str(ei.value)
        assert "no_riffle" in str(ei.value)  # the valid set is named


class TestCopyKernelIdentity:
    """The copy variants' correctness property: output == input up to
    the output layout's (known) permutation — which is exactly what
    licenses reading their timings as pure memory/grid cost."""

    def test_copy_contig_is_exact_identity(self):
        with _interpret():
            run = _build("copy_contig", ablate=af.COPY + ("no_riffle",),
                         fused=False)
            gp, sp = _inputs(run.breed)
            out = run.breed.padded(gp, sp, jax.random.key(0))
        np.testing.assert_array_equal(np.asarray(out), np.asarray(gp))

    def test_copy_riffle_is_the_riffle_permutation(self):
        with _interpret():
            run = _build("copy_riffle", ablate=af.COPY, fused=False)
            breed = run.breed
            gp, sp = _inputs(breed)
            out = np.asarray(breed.padded(gp, sp, jax.random.key(0)))
        G = breed.Pp // K
        # Row r·G + g of the output must be row g·K + r of the input —
        # the riffle-shuffle layout documented in ops/pallas_step.py.
        gp = np.asarray(gp)
        for r in (0, 1, K - 1):
            for g in (0, 1, G - 1):
                np.testing.assert_array_equal(
                    out[r * G + g], gp[g * K + r], err_msg=f"r={r} g={g}"
                )

    def test_copy_with_scores_keeps_rows_and_scores_consistent(self):
        """Fused-mode copy: genomes and score passthrough must undergo
        the SAME permutation (the score transpose in padded_ranks
        matches the genome riffle)."""
        with _interpret():
            run = _build("copy_riffle_score", ablate=af.COPY)
            breed = run.breed
            assert breed.fused
            gp, sp = _inputs(breed)
            g2, s2 = breed.padded(gp, sp, jax.random.key(0))
        np.testing.assert_allclose(
            np.asarray(s2),
            np.sum(np.asarray(g2)[:, :L], axis=1),
            rtol=1e-6,
        )

    def test_floor_variant_is_a_permutation(self):
        """All-stages-ablated floor: children are verbatim parent rows
        (selection const, no matmul/cross/mut), so the output is some
        permutation-with-replacement drawn only from input rows; under
        zero interpret-mode PRNG bits it is exactly the riffle of the
        identity selection."""
        with _interpret():
            run = _build("floor", ablate=af.FLOOR_ABLATE, fused=False)
            breed = run.breed
            gp, sp = _inputs(breed)
            out = np.asarray(breed.padded(gp, sp, jax.random.key(0)))
        rows_in = {r.tobytes() for r in np.asarray(gp)}
        rows_out = {r.tobytes() for r in out}
        assert rows_out <= rows_in


class TestAliasVariant:
    def test_alias_requires_contiguous_layout(self):
        from libpga_tpu.ops.pallas_step import make_pallas_breed

        with pytest.raises(ValueError, match="alias_io requires no_riffle"):
            make_pallas_breed(
                POP, L, deme_size=K, gene_dtype=DT, _demes_per_step=D,
                _ablate=("copy_only", "no_rank_sort", "alias_io"),
            )

    def test_alias_copy_runs_or_reports(self):
        """input_output_aliases under the interpret path: if this JAX's
        interpreter supports it the output must equal the input; a
        NotImplementedError just skips (hardware is the real target)."""
        with _interpret():
            run = _build(
                "copy_alias",
                ablate=af.COPY + ("no_riffle", "alias_io"), fused=False,
            )
            gp, sp = _inputs(run.breed)
            try:
                out = run.breed.padded(gp + 0, sp, jax.random.key(0))
            except Exception as exc:  # noqa: BLE001
                pytest.skip(f"interpret mode lacks aliasing: {exc}")
        np.testing.assert_array_equal(np.asarray(out), np.asarray(gp))


class TestPartitionArithmetic:
    MS = {
        "floor": 4.33,
        "copy_riffle": 2.80,
        "copy_contig": 2.50,
        "copy_alias": 2.30,
        "rank_sort": 0.33,
    }

    def test_components_sum_to_floor(self):
        comps, coverage = af.partition_floor(dict(self.MS))
        assert abs(sum(v for _, v, _ in comps) - self.MS["floor"]) < 1e-9
        names = [c for c, _, _ in comps]
        assert names == [
            "hbm_copy", "alias_headroom", "riffle_stride", "rank_sort",
            "kernel_scaffold",
        ]
        # directly measured = copy_riffle + rank_sort = 3.13 of 4.33
        assert coverage == pytest.approx(3.13 / 4.33)

    def test_components_sum_with_dispatch_slope(self):
        comps, coverage = af.partition_floor(
            dict(self.MS), steps_bench=256, dispatch_per_step=0.004
        )
        assert abs(sum(v for _, v, _ in comps) - self.MS["floor"]) < 1e-9
        grid = dict((c, v) for c, v, _ in comps)["grid_steps"]
        assert grid == pytest.approx(0.004 * 256)

    def test_partition_degrades_without_optional_variants(self):
        comps, coverage = af.partition_floor(
            {"floor": 4.0, "copy_riffle": 2.5, "rank_sort": 0.3}
        )
        assert abs(sum(v for _, v, _ in comps) - 4.0) < 1e-9
        assert coverage == pytest.approx(2.8 / 4.0)

    def test_fit_dispatch_slope_recovers_line(self):
        G = 2048
        a, b = 1.25, 0.004
        sweep = {d: a + b * (G / d) for d in (1, 2, 4, 8)}
        a_fit, b_fit = af.fit_dispatch_slope(sweep, G)
        assert a_fit == pytest.approx(a, abs=1e-9)
        assert b_fit == pytest.approx(b, abs=1e-12)

    def test_fit_dispatch_slope_insufficient_points(self):
        assert af.fit_dispatch_slope({4: 2.0}, 2048) == (None, None)
