"""Expression breeding operators (``ops/breed_expr.py``) — device-speed
custom crossover/mutation, the TPU answer to the reference's remaining
``__device__`` callback pointers (``pga.h:47-48``; its TSP driver's
custom crossover, ``test3/test.cu:48-64``, is the motivating workload).

Covers: XLA operator semantics, the per-gene compile restriction, the
fused-kernel path in interpret mode (padded populations included),
engine integration (kind detection, convergence, elitism), and the
C-ABI bridge's device-path guarantees. Hardware lowering is exercised
by ``capi/test_expr_breed.c`` (tests/test_capi.py) and
``tools/tpu_kernel_checks.py``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libpga_tpu.objectives import ExpressionError
from libpga_tpu.ops.breed_expr import (
    crossover_from_expression,
    mutate_from_expression,
)


class TestOperatorSemantics:
    def test_one_point_crossover_via_q(self):
        """``where(i < floor(q*L), p1, p2)`` must produce a contiguous
        p1-prefix / p2-suffix per child."""
        cx = crossover_from_expression("where(i < floor(q * L), p1, p2)")
        p1 = jnp.zeros((8, 12))
        p2 = jnp.full((8, 12), 0.9)
        rand = jax.random.uniform(jax.random.PRNGKey(0), (8, 12))
        child = np.asarray(cx.batched(p1, p2, rand))
        for row in child:
            nz = np.flatnonzero(row)
            if nz.size:  # suffix of p2 genes, no interleaving
                assert nz[-1] == 11 and np.all(np.diff(nz) == 1)

    def test_blend_stays_in_parent_hull_and_domain(self):
        cx = crossover_from_expression("r * p1 + (1 - r) * p2")
        rng = np.random.default_rng(1)
        p1 = jnp.asarray(rng.random((16, 10), dtype=np.float32))
        p2 = jnp.asarray(rng.random((16, 10), dtype=np.float32))
        rand = jax.random.uniform(jax.random.PRNGKey(1), (16, 10))
        child = np.asarray(cx.batched(p1, p2, rand))
        lo = np.minimum(np.asarray(p1), np.asarray(p2))
        hi = np.maximum(np.asarray(p1), np.asarray(p2))
        assert np.all(child >= lo - 1e-6) and np.all(child <= hi + 1e-6)
        assert np.all(child >= 0.0) and np.all(child < 1.0)

    def test_reset_mutation_rate_statistics(self):
        mx = mutate_from_expression("where(r < rate, r2, g)", rate=0.1)
        g = jnp.full((4096, 32), 0.25)
        rand = jax.random.uniform(jax.random.PRNGKey(2), (4096, 32))
        out = np.asarray(mx.batched(g, rand))
        frac = float((out != 0.25).mean())
        assert abs(frac - 0.1) < 0.01, frac
        assert mx.rate == 0.1 and mx.sigma == 0.0

    def test_result_clipped_into_gene_domain(self):
        mx = mutate_from_expression("g + 5")
        g = jnp.asarray(np.random.default_rng(3).random((4, 8), dtype=np.float32))
        out = np.asarray(mx.batched(g, jnp.zeros((4, 8))))
        assert np.all(out < 1.0) and np.all(out >= 0.0)

    def test_vector_constant_pins_genome_length(self):
        cx = crossover_from_expression(
            "where(m > 0.5, p1, p2)", m=np.ones(16, dtype=np.float32)
        )
        assert cx.pinned_genome_len == 16

    def test_cache_key_shared_across_instances(self):
        """Annealing schedules re-create operators with new rate/sigma;
        the compiled-kernel cache keys on the expression semantics, not
        the instance, so those recreations reuse one compilation."""
        from libpga_tpu.engine import _kind_key

        a = mutate_from_expression("where(r < rate, r2, g)", rate=0.1)
        b = mutate_from_expression("where(r < rate, r2, g)", rate=0.01)
        assert _kind_key(a) == _kind_key(b)
        c = mutate_from_expression("where(r < rate, g + r2, g)", rate=0.1)
        assert _kind_key(a) != _kind_key(c)
        w = np.ones(8, dtype=np.float32)
        d = crossover_from_expression("where(m > 0.5, p1, p2)", m=w)
        e = crossover_from_expression("where(m > 0.5, p1, p2)", m=w * 0.1)
        assert _kind_key(d) != _kind_key(e)  # different constant VALUES
        assert _kind_key(a) != _kind_key(
            crossover_from_expression("where(r < 0.5, p1, p2)")
        )
        assert _kind_key("point") == "point"  # builtins key by name

    def test_used_random_streams_recorded(self):
        """The kernel draws only the streams the expression references
        (review finding: a (K, Lp) PRNG tile per unused stream is real
        per-generation cost)."""
        cx = crossover_from_expression("where(i < floor(q * L), p1, p2)")
        assert cx.kernel_rows.uses == {"q"}
        mx = mutate_from_expression("where(r < rate, r2, g)")
        assert mx.kernel_rows.uses == {"r", "r2"}
        assert crossover_from_expression("p1").kernel_rows.uses == set()

    def test_per_genome_matches_batched(self):
        cx = crossover_from_expression("where(r < 0.5, p1, p2)")
        rng = np.random.default_rng(4)
        p1 = jnp.asarray(rng.random(10, dtype=np.float32))
        p2 = jnp.asarray(rng.random(10, dtype=np.float32))
        rand = jnp.asarray(rng.random(10, dtype=np.float32))
        np.testing.assert_array_equal(
            np.asarray(cx(p1, p2, rand)),
            np.asarray(cx.batched(p1[None], p2[None], rand[None])[0]),
        )


class TestCompileRestrictions:
    def test_reductions_rejected(self):
        for expr in ("sum(p1)", "p1 * mean(p2)", "min(r) + p1",
                     "dot(p1, p2)"):
            with pytest.raises(ExpressionError, match="per-gene"):
                crossover_from_expression(expr)

    def test_roll_gather_rejected(self):
        with pytest.raises(ExpressionError, match="per-gene"):
            mutate_from_expression("roll(g, 1)")
        with pytest.raises(ExpressionError, match="per-gene"):
            mutate_from_expression(
                "gather(t, g)", t=np.ones(4, dtype=np.float32)
            )

    def test_role_variables_enforced(self):
        with pytest.raises(ExpressionError, match="unknown name"):
            crossover_from_expression("where(r < 0.5, g, p2)")  # no g
        with pytest.raises(ExpressionError, match="unknown name"):
            mutate_from_expression("p1 + g")  # no parents
        with pytest.raises(ExpressionError, match="unknown name"):
            crossover_from_expression("p1 * rate")  # rate is mutate-only

    def test_elementwise_min_max_allowed(self):
        crossover_from_expression("min(p1, p2) + 0 * max(p1, p2)")

    def test_two_d_constant_rejected(self):
        with pytest.raises(ExpressionError, match="scalar or 1-D"):
            mutate_from_expression("g * c", c=np.ones((2, 3)))


class TestKernelPath:
    @pytest.mark.parametrize("pop", [256, 300])  # exact and padded
    def test_fused_kernel_interpret_mode(self, pop):
        """Expression crossover + mutation evaluate inside the breed
        kernel: children in-domain, pads inert, fused scores consistent
        with the returned genomes."""
        from jax.experimental.pallas import tpu as pltpu

        from libpga_tpu.objectives import get as get_obj
        from libpga_tpu.ops.pallas_step import make_pallas_breed

        cx = crossover_from_expression("where(i < floor(q * L), p1, p2)")
        mx = mutate_from_expression("where(r < rate, r2, g)", rate=0.05)
        obj = get_obj("onemax")
        L = 10
        g = jax.random.uniform(jax.random.PRNGKey(1), (pop, L))
        s = g.sum(axis=1)
        with pltpu.force_tpu_interpret_mode():
            breed = make_pallas_breed(
                pop, L, deme_size=128, crossover_kind=cx, mutate_kind=mx,
                fused_obj=obj.kernel_rowwise,
            )
            assert breed is not None
            g2, s2 = breed(g, s, jax.random.PRNGKey(2))
        g2, s2 = np.asarray(g2), np.asarray(s2)
        assert g2.shape == (pop, L)
        assert np.all(g2 >= 0.0) and np.all(g2 < 1.0)
        np.testing.assert_allclose(s2, g2.sum(axis=1), atol=1e-4)

    def test_multigen_kernel_interpret_mode(self):
        from jax.experimental.pallas import tpu as pltpu

        from libpga_tpu.objectives import get as get_obj
        from libpga_tpu.ops.pallas_step import make_pallas_multigen

        cx = crossover_from_expression("where(r < 0.5, p1, p2)")
        mx = mutate_from_expression("where(r < rate, r2, g)", rate=0.05)
        obj = get_obj("onemax")
        P, L = 256, 10
        g = jax.random.uniform(jax.random.PRNGKey(3), (P, L))
        s = g.sum(axis=1)
        with pltpu.force_tpu_interpret_mode():
            bm = make_pallas_multigen(
                P, L, deme_size=128, crossover_kind=cx, mutate_kind=mx,
                fused_obj=obj.kernel_rowwise,
            )
            assert bm is not None
            g2, s2 = bm(g, s, jax.random.PRNGKey(4), jnp.int32(3))
        np.testing.assert_allclose(
            np.asarray(s2), np.asarray(g2).sum(axis=1), atol=1e-4
        )

    def test_vector_const_rides_as_kernel_input(self):
        """A per-gene mask constant reaches the kernel lane-padded: the
        masked crossover takes p1 exactly where the mask says."""
        from jax.experimental.pallas import tpu as pltpu

        from libpga_tpu.objectives import get as get_obj
        from libpga_tpu.ops.pallas_step import make_pallas_breed

        L = 10
        mask = (np.arange(L) < 5).astype(np.float32)
        cx = crossover_from_expression("where(m > 0.5, p1, p2)", m=mask)
        mx = mutate_from_expression("g")  # identity
        obj = get_obj("onemax")
        g = jnp.asarray(
            np.random.default_rng(5).random((256, L), dtype=np.float32)
        )
        with pltpu.force_tpu_interpret_mode():
            breed = make_pallas_breed(
                256, L, deme_size=128, crossover_kind=cx, mutate_kind=mx,
                fused_obj=obj.kernel_rowwise,
            )
            g2, _ = breed(g, g.sum(axis=1), jax.random.PRNGKey(6))
        # every child's genes are copies of SOME population rows in the
        # masked halves: verify each child's first-half and second-half
        # each match at least one parent row exactly
        g2 = np.asarray(g2)
        gsrc = np.asarray(g)
        for row in g2[:16]:
            assert any(np.allclose(row[:5], src[:5]) for src in gsrc)
            assert any(np.allclose(row[5:], src[5:]) for src in gsrc)

    def test_pinned_length_mismatch_raises(self):
        from libpga_tpu.ops.pallas_step import make_pallas_breed

        cx = crossover_from_expression(
            "where(m > 0.5, p1, p2)", m=np.ones(16, dtype=np.float32)
        )
        with pytest.raises(ValueError, match="length-16"):
            make_pallas_breed(256, 32, crossover_kind=cx)


class TestEngineIntegration:
    def test_kind_detection_and_convergence(self):
        from libpga_tpu import PGA

        cx = crossover_from_expression(
            "where(r < 0.3, (p1 + p2) / 2, where(r2 < 0.5, p1, p2))"
        )
        mx = mutate_from_expression("where(r < rate, r2, g)", rate=0.02)
        pga = PGA(seed=0)
        h = pga.create_population(256, 16)
        pga.set_objective("onemax")
        pga.set_crossover(cx)
        pga.set_mutate(mx)
        assert pga._crossover_kind() is cx
        assert pga._mutate_kind() is mx
        # the engine's kernel mparams mirror the operator's declaration
        params = np.asarray(pga._mutate_params())
        assert params[0, 0] == np.float32(0.02)
        pga.run(40)
        _, best = pga.get_best_with_score(h)
        assert best > 13.0, best

    def test_elitism_preserved_with_expression_operators(self):
        from libpga_tpu import PGA, PGAConfig

        cx = crossover_from_expression("where(r < 0.5, p1, p2)")
        mx = mutate_from_expression("where(r < rate, r2, g)", rate=0.5)
        pga = PGA(seed=3, config=PGAConfig(elitism=2))
        h = pga.create_population(128, 12)
        pga.set_objective("onemax")
        pga.set_crossover(cx)
        pga.set_mutate(mx)
        pga.evaluate(h)
        top_before = float(jnp.max(pga.population(h).scores))
        pga.run(5)
        top_after = float(jnp.max(pga.population(h).scores))
        assert top_after >= top_before - 1e-5

    def test_islands_with_expression_operators(self):
        """run_islands works with expression breeding operators
        installed (the island breed builder receives the operator as
        its kernel kind on TPU; the XLA path serves here)."""
        from libpga_tpu import PGA

        cx = crossover_from_expression("where(i < floor(q * L), p1, p2)")
        mx = mutate_from_expression("where(r < rate, r2, g)", rate=0.03)
        pga = PGA(seed=5)
        for _ in range(4):
            pga.create_population(128, 12)
        pga.set_objective("onemax")
        pga.set_crossover(cx)
        pga.set_mutate(mx)
        gens = pga.run_islands(30, 10, 0.1)
        assert gens == 30
        best = max(pga.get_best_with_score(h)[1] for h in pga._handles())
        assert best > 9.5, best

    def test_null_restore_returns_builtin_kinds(self):
        from libpga_tpu import PGA

        pga = PGA(seed=0)
        pga.create_population(128, 8)
        pga.set_crossover(crossover_from_expression("p1"))
        pga.set_mutate(mutate_from_expression("g"))
        pga.set_crossover(None)
        pga.set_mutate(None)
        assert pga._crossover_kind() == "uniform"
        assert pga._mutate_kind() == "point"


class TestCapiBridge:
    def test_expr_breeding_stays_on_device(self):
        """Unlike the host-pointer path, expression breeding operators
        must NOT pin the solver to the CPU backend, and must expose the
        kernel hook (the verdict item-1 'no pure_callback, no CPU pin'
        contract)."""
        from libpga_tpu import capi_bridge as cb

        h = cb.init(9)
        try:
            cb.create_population(h, 256, 16, 0)
            cb.set_objective_name(h, "onemax")
            cb.set_crossover_expr(h, "where(i < floor(q * L), p1, p2)")
            cb.set_mutate_expr(h, "where(r < rate, r2, g)", 0.05, -1.0)
            pga = cb._solver(h)
            assert not cb._host_ops.get(h), "expr breeding pinned to CPU"
            assert pga.config.use_pallas is None  # auto stays
            assert getattr(pga._crossover, "kernel_rows", None) is not None
            assert getattr(pga._mutate, "kernel_rows", None) is not None
            assert pga._mutate.rate == np.float32(0.05)
            # and the solver still evolves
            gens = pga.run(5)
            assert gens == 5
        finally:
            cb.deinit(h)

    def test_expr_breeding_error_paths(self):
        from libpga_tpu import capi_bridge as cb

        h = cb.init(10)
        try:
            cb.create_population(h, 128, 8, 0)
            with pytest.raises(ExpressionError):
                cb.set_crossover_expr(h, "sum(p1)")
            with pytest.raises(ExpressionError):
                cb.set_mutate_expr(h, "where(", -1.0, -1.0)
            # a registered 2-D gather table is NOT forwarded to the
            # breeding factories (strictly per-gene)
            cb.set_objective_expr_const2(
                h, "T", np.ones(8 * 4, dtype=np.float32).tobytes(), 4, 8
            )
            cb.set_crossover_expr(h, "where(r < 0.5, p1, p2)")  # ok
        finally:
            cb.deinit(h)

    def test_colliding_const_name_does_not_block_breeding(self):
        """A constant registered under a breeding-variable name (legal
        for objectives) must not fail every later set_*_expr — it is
        dropped from the forwarded set (the parser resolves variables
        first, so it could never be referenced anyway)."""
        from libpga_tpu import capi_bridge as cb

        h = cb.init(12)
        try:
            cb.create_population(h, 128, 8, 0)
            cb.set_objective_expr_const(
                h, "q", np.float32(2.0).tobytes()
            )
            cb.set_objective_expr(h, "sum(g) * q")  # objective uses it
            cb.set_crossover_expr(h, "where(r < 0.5, p1, p2)")  # review fix
            cb.set_mutate_expr(h, "where(r < rate, r2, g)", 0.05, -1.0)
        finally:
            cb.deinit(h)

    def test_breeding_pin_checked_at_create_population(self):
        """A population created AFTER a breeding expression with vector
        constants gets the set-time length diagnostic (review finding) —
        not a mid-run kernel-build error."""
        from libpga_tpu import capi_bridge as cb

        h = cb.init(11)
        try:
            cb.set_objective_expr_const(
                h, "m", np.ones(16, dtype=np.float32).tobytes()
            )
            cb.set_crossover_expr(h, "where(m > 0.5, p1, p2)")
            cb.create_population(h, 128, 16, 0)  # matching: ok
            with pytest.raises(ValueError, match="length-16"):
                cb.create_population(h, 128, 32, 0)
            assert cb._solver(h).num_populations == 1
        finally:
            cb.deinit(h)


def test_capi_expression_breeding_driver(built_shim):
    """The C smoke driver: non-builtin crossover+mutation expressions
    drive OneMax from C at device speed; error paths return -1; NULL
    restores the defaults."""
    out = _run(built_shim, "test_expr_breed")
    assert "blend+creep best" in out
    assert "one-point+reset best" in out


# Reuse test_capi's build fixture + runner for the C driver test.
from tests.test_capi import _run, built_shim  # noqa: E402,F401
