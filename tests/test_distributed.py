"""Multi-process distributed smoke, as a test.

Runs ``tools/multihost_smoke.py`` — two worker processes, a shared
8-device global CPU mesh via ``jax.distributed.initialize``, sharded
island GA with cross-process ring migration, engine-path run with an
``AutoCheckpointer`` (populations half non-addressable per process),
per-process shard checkpoint save + merged restore — and asserts the
harness's own verdict. This is the test the reference's "+MPI" claim
never had (survey §2.3: zero MPI code in the tree).
"""

import os
import subprocess
import sys
from pathlib import Path

import jax
import pytest

TOOL = Path(__file__).resolve().parent.parent / "tools" / "multihost_smoke.py"

# Multi-process CPU collectives only exist in newer jaxlib: 0.4.x raises
# "Multiprocess computations aren't implemented on the CPU backend" at
# the first sharded computation, so on those versions the smokes cannot
# run AT ALL on this platform (they still run on real multi-host TPU).
# Proxy capability gate: jax.shard_map moved to the top level in the
# same era the CPU backend gained cross-process computations.
_MULTIPROC_CPU = hasattr(jax, "shard_map")
needs_multiproc_cpu = pytest.mark.skipif(
    not _MULTIPROC_CPU,
    reason="installed jaxlib has no multi-process CPU collectives",
)


@pytest.mark.slow
@needs_multiproc_cpu
def test_multihost_smoke_with_checkpointing():
    proc = subprocess.run(
        [sys.executable, str(TOOL)],
        capture_output=True,
        text=True,
        timeout=420,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, (
        f"multihost smoke failed:\n{proc.stdout}\n{proc.stderr}"
    )
    assert "MULTIHOST SMOKE: PASS" in proc.stdout
    assert "checkpoint best" in proc.stdout


RESIZE_TOOL = Path(__file__).resolve().parent.parent / "tools" / "resize_smoke.py"


@pytest.mark.slow
@needs_multiproc_cpu
def test_job_resize_checkpoint_matrix():
    """The multi-process matrix (tools/resize_smoke.py), widened to an
    8-PROCESS fleet in round 5 (verdict item 9): a 4-process fleet runs
    the sharded island GA and shard-saves; a 2-process fleet restores
    it (resize DOWN: more shard files than processes), verifies the
    global best survived exactly, evolves, and saves again at the same
    path; an 8-process fleet — one process per device, the full-fleet
    shape — restores THAT (resize UP, with stage-1's stale proc2/proc3
    files still on disk — restore must honor the checkpoint's declared
    file set), evolves, and saves 8 shards; a 4-process fleet restores
    the 8-shard checkpoint (resize DOWN again). Asserts the harness's
    own verdict."""
    proc = subprocess.run(
        [sys.executable, str(RESIZE_TOOL)],
        capture_output=True,
        text=True,
        timeout=1500,  # 4 stages (the 8-process stage is the heaviest)
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, (
        f"resize smoke failed:\n{proc.stdout}\n{proc.stderr}"
    )
    assert "RESIZE SMOKE: PASS" in proc.stdout
    assert "restored best" in proc.stdout
