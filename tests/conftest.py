"""Test harness config: force a simulated 8-device CPU platform.

The reference cannot run without a physical CUDA device (every path hits
cudaMalloc/kernel launches — survey §4); this is the "fake backend" it
lacks. Must run before jax initializes a backend.
"""

import os

# Env vars alone are not enough here: the container's sitecustomize imports
# jax._src at interpreter start (capturing JAX_PLATFORMS=axon), so the
# platform must be overridden through jax.config before backend init.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
jax.config.update("jax_threefry_partitionable", True)

import pytest  # noqa: E402


@pytest.fixture
def key():
    return jax.random.key(0)
