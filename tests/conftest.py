"""Test harness config: force a simulated 8-device CPU platform.

The reference cannot run without a physical CUDA device (every path hits
cudaMalloc/kernel launches — survey §4); this is the "fake backend" it
lacks. Must run before jax initializes a backend.
"""

import os

# Env vars alone are not enough here: the container's sitecustomize imports
# jax._src at interpreter start (capturing JAX_PLATFORMS=axon), so the
# platform must be overridden through jax.config before backend init.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# jax_num_cpu_devices only exists in newer JAX (>= 0.4.34 it appeared,
# but 0.4.37 as installed here still lacks it); the XLA_FLAGS fallback
# above already forces 8 host devices on versions without the option.
if hasattr(jax.config, "jax_num_cpu_devices"):
    jax.config.update("jax_num_cpu_devices", 8)
jax.config.update("jax_threefry_partitionable", True)

import pytest  # noqa: E402


def _install_pallas_interpret_compat() -> None:
    """Version-gate ``pltpu.force_tpu_interpret_mode`` for old JAX.

    The kernel structure tests run the Mosaic kernels on CPU via
    ``pltpu.force_tpu_interpret_mode``, which the installed JAX 0.4.37
    predates. The shim reproduces the two properties those tests rely
    on: every ``pl.pallas_call`` built inside the context runs with
    ``interpret=True``, and the Mosaic-only PRNG primitives execute on
    CPU with the SAME semantics the real interpret mode documents —
    ``prng_random_bits`` yields all-ZERO bits (the structure tests'
    determinism anchor, see tests/test_pallas.py docstring) and
    ``prng_seed`` is a no-op. ``bitcast`` already carries a generic
    lowering rule. On newer JAX the real context manager is used
    untouched.
    """
    from jax.experimental.pallas import tpu as pltpu

    if hasattr(pltpu, "force_tpu_interpret_mode"):
        return
    import contextlib

    import jax.numpy as jnp
    from jax.interpreters import mlir
    from jax._src.pallas.mosaic import primitives as _mp
    from jax.experimental import pallas as pl

    mlir.register_lowering(
        _mp.prng_seed_p,
        mlir.lower_fun(lambda *seeds: [], multiple_results=True),
        "cpu",
    )
    mlir.register_lowering(
        _mp.prng_random_bits_p,
        mlir.lower_fun(
            lambda *, shape: jnp.zeros(shape, jnp.int32),
            multiple_results=False,
        ),
        "cpu",
    )

    _real_call = pl.pallas_call

    @contextlib.contextmanager
    def force_tpu_interpret_mode():
        def interpret_call(*args, **kwargs):
            kwargs["interpret"] = True
            return _real_call(*args, **kwargs)

        pl.pallas_call = interpret_call
        try:
            yield
        finally:
            pl.pallas_call = _real_call

    pltpu.force_tpu_interpret_mode = force_tpu_interpret_mode


_install_pallas_interpret_compat()


@pytest.fixture
def key():
    return jax.random.key(0)
