"""Build + run the native C ABI shims (capi/) against the CPU backend.

These tests compile both shim flavors — ``libpga_tpu_c.so`` (the improved
int-returning ABI) and ``libpga.so`` (the exact-reference ABI from the
reference repo's ``include/pga.h``) — and run their C smoke drivers as
subprocesses:

- ``test_onemax``: builtin named objective, the reference ``test/test.cu``
  workload shape;
- ``test_custom_obj``: a custom HOST C objective function pointer
  (bounded knapsack, the reference ``test2/test.cu`` workload) through
  the ctypes + pure_callback compatibility path;
- ``test_islands``: improved-ABI coverage of the island run loop, both
  migrations, top-k getters, the step-by-step operator chain, and early
  termination;
- ``test_compat``: the full exact-reference ABI surface, including
  custom mutate/crossover host pointers and the ``gene**`` ownership
  contract of the top-k getters;
- source-compat proof: the reference's own knapsack driver
  (``test2/test.cu``) de-CUDA'd mechanically at test time (drop
  ``__device__``/``__constant__``, assign the function pointer directly
  instead of ``cudaMemcpyFromSymbol``) compiles against ``capi/pga.h``
  and runs to completion.
"""

import os
import re
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

CAPI = Path(__file__).resolve().parent.parent / "capi"
REPO = CAPI.parent
REFERENCE_DRIVER = Path("/root/reference/test2/test.cu")


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = f"{REPO}:{env.get('PYTHONPATH', '')}"
    return env


@pytest.fixture(scope="module")
def built_shim():
    if shutil.which("g++") is None or shutil.which("make") is None:
        pytest.skip("no native toolchain")
    proc = subprocess.run(
        ["make", "-C", str(CAPI), f"PYTHON={sys.executable}"],
        capture_output=True,
        text=True,
    )
    if proc.returncode != 0:
        pytest.fail(f"capi build failed:\n{proc.stdout}\n{proc.stderr}")
    return CAPI


def _run(built, name, timeout=420):
    proc = subprocess.run(
        [str(built / name)],
        capture_output=True,
        text=True,
        env=_env(),
        timeout=timeout,
    )
    assert proc.returncode == 0, (
        f"{name} failed (rc={proc.returncode}):\n{proc.stdout}\n{proc.stderr}"
    )
    assert "PASS" in proc.stdout
    return proc.stdout


def test_capi_onemax_builtin_objective(built_shim):
    out = _run(built_shim, "test_onemax")
    assert "onemax best sum" in out


def test_capi_custom_host_objective(built_shim):
    out = _run(built_shim, "test_custom_obj")
    assert "knapsack best" in out


def test_capi_islands_and_topk(built_shim):
    out = _run(built_shim, "test_islands")
    assert "islands best sum" in out


def test_capi_compat_full_abi(built_shim):
    out = _run(built_shim, "test_compat")
    assert "compat best sum" in out


def _decuda(src: str) -> str:
    """The minimal mechanical CUDA→host transform for reference drivers:
    drop the __device__/__constant__ qualifiers and replace the
    cudaMemcpyFromSymbol device-pointer fetch with a direct assignment.
    Nothing else changes."""
    src = src.replace("__constant__ ", "").replace("__device__ ", "")
    return re.sub(
        r"cudaMemcpyFromSymbol\(\s*&(\w+)\s*,\s*(\w+)\s*,.*;",
        r"\1 = (void *)\2;",
        src,
    )


@pytest.mark.skipif(
    not REFERENCE_DRIVER.exists(), reason="reference tree not mounted"
)
def test_reference_driver_source_compat(built_shim, tmp_path):
    """The reference's own knapsack driver source, de-CUDA'd mechanically,
    must compile against capi/pga.h and run correctly against libpga.so —
    the drop-in source-compatibility contract."""
    driver_c = tmp_path / "ref_test2.c"
    driver_c.write_text(_decuda(REFERENCE_DRIVER.read_text()))

    exe = tmp_path / "ref_test2"
    proc = subprocess.run(
        [
            "gcc", "-std=gnu11", "-O2",
            # the driver calls free() without <stdlib.h> (nvcc's headers
            # pull it in); keep the source untouched and allow the
            # implicit declaration instead
            "-Wno-implicit-function-declaration",
            f"-I{CAPI}", str(driver_c), "-o", str(exe),
            f"-L{CAPI}", "-lpga", f"-Wl,-rpath,{CAPI}",
        ],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, (
        f"de-CUDA'd reference driver failed to compile:\n{proc.stderr}"
    )

    run = subprocess.run(
        [str(exe)], capture_output=True, text=True, env=_env(), timeout=420
    )
    assert run.returncode == 0, (
        f"reference driver run failed (rc={run.returncode}):\n"
        f"{run.stdout}\n{run.stderr}"
    )
    # the driver prints the chosen per-item counts: 6 ints in [0, 2]
    counts = [int(tok) for tok in run.stdout.split()]
    assert len(counts) == 6
    assert all(0 <= c <= 2 for c in counts)
