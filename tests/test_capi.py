"""Build + run the native C ABI shims (capi/) against the CPU backend.

These tests compile both shim flavors — ``libpga_tpu_c.so`` (the improved
int-returning ABI) and ``libpga.so`` (the exact-reference ABI from the
reference repo's ``include/pga.h``) — and run their C smoke drivers as
subprocesses:

- ``test_onemax``: builtin named objective, the reference ``test/test.cu``
  workload shape;
- ``test_custom_obj``: a custom HOST C objective function pointer
  (bounded knapsack, the reference ``test2/test.cu`` workload) through
  the ctypes + pure_callback compatibility path;
- ``test_islands``: improved-ABI coverage of the island run loop, both
  migrations, top-k getters, the step-by-step operator chain, and early
  termination;
- ``test_compat``: the full exact-reference ABI surface, including
  custom mutate/crossover host pointers and the ``gene**`` ownership
  contract of the top-k getters;
- source-compat proof: ALL THREE of the reference's own drivers —
  ``test/test.cu`` (custom objective at 40k×100), ``test2/test.cu``
  (knapsack), and ``test3/test.cu`` (TSP: custom crossover,
  ``__constant__`` city matrix via ``cudaMemcpyToSymbol``, stdin input
  from ``gen.c``) — de-CUDA'd mechanically at test time (drop
  ``__device__``/``__constant__``, ``cudaMemcpyFromSymbol`` → direct
  assignment, ``cudaMemcpyToSymbol`` → ``memcpy``) compile against
  ``capi/pga.h`` and run correctly against ``libpga.so``;
- batched marshaling: the host-callback row loop runs in C
  (``capi/pga_rowloop.c``) — asserted ≥5× faster than the Python loop
  at 40k×100 with bit-identical results.
"""

import os
import re
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

CAPI = Path(__file__).resolve().parent.parent / "capi"
REPO = CAPI.parent
REFERENCE_DRIVER = Path("/root/reference/test2/test.cu")


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = f"{REPO}:{env.get('PYTHONPATH', '')}"
    return env


@pytest.fixture(scope="module")
def built_shim():
    if shutil.which("g++") is None or shutil.which("make") is None:
        pytest.skip("no native toolchain")
    proc = subprocess.run(
        ["make", "-C", str(CAPI), f"PYTHON={sys.executable}"],
        capture_output=True,
        text=True,
    )
    if proc.returncode != 0:
        pytest.fail(f"capi build failed:\n{proc.stdout}\n{proc.stderr}")
    return CAPI


def _run(built, name, timeout=420):
    proc = subprocess.run(
        [str(built / name)],
        capture_output=True,
        text=True,
        env=_env(),
        timeout=timeout,
    )
    assert proc.returncode == 0, (
        f"{name} failed (rc={proc.returncode}):\n{proc.stdout}\n{proc.stderr}"
    )
    assert "PASS" in proc.stdout
    return proc.stdout


def test_capi_onemax_builtin_objective(built_shim):
    out = _run(built_shim, "test_onemax")
    assert "onemax best sum" in out


def test_capi_custom_host_objective(built_shim):
    out = _run(built_shim, "test_custom_obj")
    assert "knapsack best" in out


def test_capi_islands_and_topk(built_shim):
    out = _run(built_shim, "test_islands")
    assert "islands best sum" in out


def test_capi_compat_full_abi(built_shim):
    out = _run(built_shim, "test_compat")
    assert "compat best sum" in out


def test_capi_telemetry_history(built_shim):
    """pga_set_telemetry + pga_get_history: the on-device per-generation
    history is reachable from C — shape, NaN-free rows, convergence
    recorded, and the disabled/NULL surfaces behave (ISSUE 2: history
    reachable from both Python and the C ABI)."""
    out = _run(built_shim, "test_telemetry")
    assert "telemetry history:" in out


def test_capi_serving_submit_poll_await(built_shim):
    """pga_submit/pga_poll/pga_await: async batched serving round trip
    from C — tickets pending below max_batch, done once the bucket
    fills, awaited results bit-identical to a same-seed synchronous
    pga_run, and the NULL/stale-ticket error surfaces (ISSUE 4)."""
    _run(built_shim, "test_serving")


def test_capi_selection_strategies(built_shim):
    """pga_set_selection: TRUNCATION and LINEAR_RANK converge from C;
    out-of-range params and unknown enum values return -1."""
    out = _run(built_shim, "test_selection")
    assert "truncation(0.25) best sum" in out
    assert "linear_rank best sum" in out


def test_capi_expression_objective(built_shim):
    """pga_set_objective_expr: a vector-constant weighted objective and
    a sphere-style expression both drive the GA from C, and every
    malformed expression returns -1 without corrupting the solver
    (device-speed custom objectives — the reference's __device__
    pointer surface, pga.h:66, done the TPU way)."""
    out = _run(built_shim, "test_expr_obj")
    assert "weighted onemax" in out
    assert "sphere residual" in out


def test_capi_expression_objective_stays_on_device(built_shim):
    """Unlike the host-pointer path, an expression objective must NOT
    pin the solver to the CPU backend, and must expose the fusable
    rowwise form the Pallas kernel consumes."""
    import numpy as np

    from libpga_tpu import capi_bridge as cb

    h = cb.init(5)
    try:
        cb.create_population(h, 256, 16, 0)
        cb.set_objective_expr_const(
            h, "w", np.arange(16, dtype=np.float32).tobytes()
        )
        cb.set_objective_expr(h, "dot(w, g)")
        pga = cb._solver(h)
        assert not cb._host_ops.get(h), "expr objective pinned solver to CPU"
        assert pga.config.use_pallas is None  # auto (accelerator) stays
        assert getattr(pga._objective, "kernel_rowwise", None) is not None
        assert len(pga._objective.kernel_rowwise_consts) == 1
        # and it actually evaluates
        cb.evaluate(h, 0)
        assert np.isfinite(float(pga.populations[0].scores.max()))
    finally:
        cb.deinit(h)


def test_capi_tsp_coords_and_named_operators(built_shim):
    """pga_set_objective_tsp_coords + pga_set_crossover_name('order') +
    pga_set_mutate_name('swap'): the reference's flagship test3 workload
    as a first-class C path at device speed, 160 cities (beyond the
    reference's 110-city cap) — best tour is a full permutation; both
    duplicate modes run; unknown names return -1. Explicit timeout: the
    XLA order-crossover scan on the CPU backend measured ~31 s solo but
    multiplies under suite-parallel CPU load."""
    out = _run(built_shim, "test_tsp", timeout=900)
    assert "fused TSP: 160/160 unique cities" in out
    assert "pairs-mode TSP" in out


def test_named_operators_bridge_semantics():
    """Bridge level: named kinds map to the kernel-implementable
    builtin operators (no CPU pin, kernel kinds detected) and carry
    their runtime parameters."""
    import numpy as np

    from libpga_tpu import capi_bridge as cb

    h = cb.init(13)
    try:
        cb.create_population(h, 256, 16, 0)
        cb.set_crossover_name(h, "order")
        cb.set_mutate_name(h, "swap", 0.7, -1.0)
        pga = cb._solver(h)
        assert not cb._host_ops.get(h)
        assert pga._crossover_kind() == "order"
        assert pga._mutate_kind() == "swap"
        assert pga._mutate.rate == 0.7
        cb.set_mutate_name(h, "gaussian", 0.2, 0.05)
        assert pga._mutate_kind() == "gaussian"
        assert pga._mutate.sigma == np.float32(0.05)
        # TSP coords objective: genes mode carries the kernel hook
        coords = np.random.default_rng(0).random((16, 2)).astype(np.float32)
        cb.set_objective_tsp_coords(h, coords.tobytes(), 16, -1.0, 1)
        assert getattr(pga._objective, "kernel_gene_major", None) is not None
        cb.set_objective_tsp_coords(h, coords.tobytes(), 16, -1.0, 0)
        assert getattr(pga._objective, "kernel_gene_major", None) is None
        with pytest.raises(ValueError, match="expected 2"):
            cb.set_objective_tsp_coords(h, coords.tobytes(), 20, -1.0, 1)
    finally:
        cb.deinit(h)


def test_expr_vector_const_checked_at_create_population(built_shim):
    """A population created AFTER an expression objective with vector
    constants is installed gets the same set-time length diagnostic as
    one existing before (round-4 advisor finding) — not a raw broadcast
    error inside the first jitted evaluate."""
    import numpy as np
    import pytest

    from libpga_tpu import capi_bridge as cb

    h = cb.init(7)
    try:
        cb.set_objective_expr_const(
            h, "w", np.arange(16, dtype=np.float32).tobytes()
        )
        cb.set_objective_expr(h, "dot(w, g)")  # no populations yet: ok
        cb.create_population(h, 128, 16, 0)  # matching length: ok
        with pytest.raises(ValueError, match="length-16 vector constant"):
            cb.create_population(h, 128, 24, 0)
        assert cb._solver(h).num_populations == 1  # failed create added none
        cb.evaluate(h, 0)
    finally:
        cb.deinit(h)


def test_rowloop_batched_marshaling_speedup_and_parity(built_shim, tmp_path):
    """Host-callback marshaling must loop over rows in C, not Python:
    one Python<->C crossing per generation (round-2 verdict finding).
    Asserts the C row loop returns bit-identical scores and is >= 5x
    faster than the Python fallback at the reference's 40k x 100 shape."""
    import ctypes
    import time

    import numpy as np

    from libpga_tpu import capi_bridge as cb

    obj_src = tmp_path / "obj.c"
    obj_src.write_text(
        "float sum_obj(float *g, unsigned n) {\n"
        "    float s = 0;\n"
        "    for (unsigned i = 0; i < n; ++i) s += g[i];\n"
        "    return s;\n"
        "}\n"
    )
    obj_so = tmp_path / "obj.so"
    subprocess.run(
        ["gcc", "-O2", "-fPIC", "-shared", str(obj_src), "-o", str(obj_so)],
        check=True,
    )
    lib = ctypes.CDLL(str(obj_so))
    addr = ctypes.cast(lib.sum_obj, ctypes.c_void_p).value

    h = cb.init(0)
    try:
        p = cb.create_population(h, 40_000, 100, 0)
        cb.set_objective_ptr(h, addr)
        assert cb._rowloop_lib() is not None, "row-loop library must load"

        def timed_eval():
            t0 = time.perf_counter()
            cb.evaluate(h, p)
            return time.perf_counter() - t0

        from libpga_tpu.engine import PopulationHandle

        def all_scores():
            return np.asarray(
                cb._solver(h).population(PopulationHandle(p)).scores
            )

        timed_eval()  # compile
        t_c = min(timed_eval() for _ in range(3))
        scores_c = all_scores()

        cb._ROWLOOP = False  # force the Python row-loop fallback
        try:
            timed_eval()
            t_py = min(timed_eval() for _ in range(2))
            scores_py = all_scores()
        finally:
            cb._ROWLOOP = None  # re-probe on next use

        # every stored fitness value, not just the argmax genome
        np.testing.assert_array_equal(scores_c, scores_py)
        assert t_py / t_c >= 5, (
            f"C row loop only {t_py / t_c:.1f}x faster "
            f"(C {t_c * 1e3:.1f} ms, Python {t_py * 1e3:.1f} ms)"
        )
    finally:
        cb.deinit(h)


def _decuda(src: str) -> str:
    """The minimal mechanical CUDA→host transform for reference drivers:
    drop the __device__/__constant__ qualifiers, replace the
    cudaMemcpyFromSymbol device-pointer fetch with a direct assignment,
    and cudaMemcpyToSymbol with memcpy (same dst/src/size argument
    order). Nothing else changes."""
    src = src.replace("__constant__ ", "").replace("__device__ ", "")
    src = src.replace("cudaMemcpyToSymbol(", "memcpy(")
    return re.sub(
        r"cudaMemcpyFromSymbol\(\s*&(\w+)\s*,\s*(\w+)\s*,.*;",
        r"\1 = (void *)\2;",
        src,
    )


def _compile_decuda_driver(driver_path: Path, tmp_path: Path, name: str):
    out_c = tmp_path / f"{name}.c"
    out_c.write_text(_decuda(driver_path.read_text()))
    exe = tmp_path / name
    proc = subprocess.run(
        [
            "gcc", "-std=gnu11", "-O2",
            # nvcc's headers pull in stdlib/string prototypes the drivers
            # rely on implicitly; keep the sources untouched and allow
            # the implicit declarations instead
            "-Wno-implicit-function-declaration",
            f"-I{CAPI}", str(out_c), "-o", str(exe),
            f"-L{CAPI}", "-lpga", f"-Wl,-rpath,{CAPI}",
        ],
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, (
        f"de-CUDA'd {driver_path} failed to compile:\n{proc.stderr}"
    )
    return exe


@pytest.mark.skipif(
    not REFERENCE_DRIVER.exists(), reason="reference tree not mounted"
)
def test_reference_driver_source_compat(built_shim, tmp_path):
    """The reference's own knapsack driver source (test2/test.cu),
    de-CUDA'd mechanically, must compile against capi/pga.h and run
    correctly against libpga.so — the drop-in source-compatibility
    contract."""
    exe = _compile_decuda_driver(REFERENCE_DRIVER, tmp_path, "ref_test2")
    run = subprocess.run(
        [str(exe)], capture_output=True, text=True, env=_env(), timeout=420
    )
    assert run.returncode == 0, (
        f"reference driver run failed (rc={run.returncode}):\n"
        f"{run.stdout}\n{run.stderr}"
    )
    # the driver prints the chosen per-item counts: 6 ints in [0, 2]
    counts = [int(tok) for tok in run.stdout.split()]
    assert len(counts) == 6
    assert all(0 <= c <= 2 for c in counts)


REFERENCE_DRIVER_ONEMAX = Path("/root/reference/test/test.cu")
REFERENCE_DRIVER_TSP = Path("/root/reference/test3/test.cu")
REFERENCE_TSP_GEN = Path("/root/reference/test3/gen.c")


@pytest.mark.skipif(
    not REFERENCE_DRIVER_ONEMAX.exists(), reason="reference tree not mounted"
)
def test_reference_onemax_driver_source_compat(built_shim, tmp_path):
    """The reference's first driver (test/test.cu): a custom host
    objective function pointer at the full 40,000 x 100 scale, 100
    generations. Feasible through the compat path because the callback
    marshaling row loop runs in C (one crossing per generation)."""
    exe = _compile_decuda_driver(REFERENCE_DRIVER_ONEMAX, tmp_path, "ref_test1")
    run = subprocess.run(
        [str(exe)], capture_output=True, text=True, env=_env(), timeout=420
    )
    assert run.returncode == 0, (
        f"onemax reference driver failed (rc={run.returncode}):\n"
        f"{run.stdout}\n{run.stderr}"
    )


@pytest.mark.skipif(
    not REFERENCE_DRIVER_TSP.exists(), reason="reference tree not mounted"
)
def test_reference_tsp_driver_source_compat(built_shim, tmp_path):
    """The reference's third driver (test3/test.cu): custom objective AND
    custom crossover host pointers, a __constant__ city matrix loaded via
    cudaMemcpyToSymbol (de-CUDA'd to memcpy), city input on stdin from
    the reference's own gen.c generator, and a freed pga_get_best result
    (gene* ownership contract)."""
    gen_exe = tmp_path / "gen"
    proc = subprocess.run(
        ["gcc", "-O2", str(REFERENCE_TSP_GEN), "-o", str(gen_exe)],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, f"gen.c failed to compile:\n{proc.stderr}"
    gen_run = subprocess.run(
        [str(gen_exe)], capture_output=True, text=True, timeout=60
    )
    assert gen_run.returncode == 0, f"gen failed:\n{gen_run.stderr}"
    cities = gen_run.stdout

    exe = _compile_decuda_driver(REFERENCE_DRIVER_TSP, tmp_path, "ref_test3")
    run = subprocess.run(
        [str(exe)], input=cities, capture_output=True, text=True,
        env=_env(), timeout=420,
    )
    assert run.returncode == 0, (
        f"tsp reference driver failed (rc={run.returncode}):\n"
        f"{run.stdout[-2000:]}\n{run.stderr[-2000:]}"
    )
    # The driver prints the best tour as 100 decoded city indices (plus
    # "HERE" markers if any duplicates survived — the reference does the
    # same). Valid result: exactly 100 in-range indices, mostly unique
    # (random decoding would give ~63 unique; an evolved tour far more).
    tour = [int(t) for t in run.stdout.split() if t.lstrip("-").isdigit()]
    assert len(tour) == 100
    assert all(0 <= c < 100 for c in tour)
    assert len(set(tour)) >= 80, (
        f"evolved tour only has {len(set(tour))}/100 unique cities"
    )
