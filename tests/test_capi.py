"""Build + run the native C ABI shim (capi/) against the CPU backend.

These tests compile ``libpga_tpu_c.so`` (a C++ shared library embedding
CPython that forwards the reference-shaped ``pga_*`` C API to this
package) and run its two C smoke drivers as subprocesses:

- ``test_onemax``: builtin named objective, the reference ``test/test.cu``
  workload shape;
- ``test_custom_obj``: a custom HOST C objective function pointer
  (bounded knapsack, the reference ``test2/test.cu`` workload) through
  the ctypes + pure_callback compatibility path.
"""

import os
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

CAPI = Path(__file__).resolve().parent.parent / "capi"
REPO = CAPI.parent


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = f"{REPO}:{env.get('PYTHONPATH', '')}"
    return env


@pytest.fixture(scope="module")
def built_shim():
    if shutil.which("g++") is None or shutil.which("make") is None:
        pytest.skip("no native toolchain")
    proc = subprocess.run(
        ["make", "-C", str(CAPI), f"PYTHON={sys.executable}"],
        capture_output=True,
        text=True,
    )
    if proc.returncode != 0:
        pytest.fail(f"capi build failed:\n{proc.stdout}\n{proc.stderr}")
    return CAPI


def _run(built, name, timeout=420):
    proc = subprocess.run(
        [str(built / name)],
        capture_output=True,
        text=True,
        env=_env(),
        timeout=timeout,
    )
    assert proc.returncode == 0, (
        f"{name} failed (rc={proc.returncode}):\n{proc.stdout}\n{proc.stderr}"
    )
    assert "PASS" in proc.stdout
    return proc.stdout


def test_capi_onemax_builtin_objective(built_shim):
    out = _run(built_shim, "test_onemax")
    assert "onemax best sum" in out


def test_capi_custom_host_objective(built_shim):
    out = _run(built_shim, "test_custom_obj")
    assert "knapsack best" in out
