"""Engine/API integration tests: lifecycle, run loops, convergence on known
optima, early termination, step-by-step operator parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import libpga_tpu as pga_mod
from libpga_tpu import PGA, PGAConfig
from libpga_tpu.engine import PopulationHandle


def test_lifecycle_and_population_guards():
    pga = PGA(seed=0)
    with pytest.raises(ValueError):
        pga.create_population(10, 3)  # genome_len >= 4 (reference pga.cu:184)
    h = pga.create_population(100, 8)
    assert pga.population(h).size == 100
    assert pga.population(h).genome_len == 8


def test_max_populations_guard():
    pga = PGA(seed=0, config=PGAConfig(max_populations=2))
    pga.create_population(10, 4)
    pga.create_population(10, 4)
    with pytest.raises(RuntimeError):
        pga.create_population(10, 4)


def test_run_onemax_converges():
    # The reference's first driver workload, scaled down (test/test.cu).
    pga = PGA(seed=0)
    h = pga.create_population(2000, 32)
    pga.set_objective("onemax")
    gens = pga.run(60)
    assert gens == 60
    g, s = pga.get_best_with_score(h)
    assert s > 0.85 * 32  # random init averages 16; GA must push toward 32


def test_run_early_termination():
    pga = PGA(seed=0)
    pga.create_population(2000, 16)
    pga.set_objective("onemax")
    gens = pga.run(10_000, target=13.0)
    assert gens < 10_000  # stopped when best >= 13


def test_run_requires_objective():
    pga = PGA(seed=0)
    pga.create_population(10, 4)
    with pytest.raises(RuntimeError):
        pga.run(1)


def test_knapsack_driver_workload():
    # Reference second driver: pop 100, 6 items, 5 gens (test2/test.cu:43,49).
    pga = PGA(seed=1)
    h = pga.create_population(100, 6)
    pga.set_objective("knapsack")
    pga.run(30)
    g, s = pga.get_best_with_score(h)
    counts = np.floor(np.asarray(g) * 2).astype(int)
    # Best known: item2 once (w6 v250) + item3 once (w4 v35) = w10 v285.
    assert s > 0  # feasible
    weights = np.array([7, 8, 6, 4, 3, 9])
    assert (counts * weights).sum() <= 10
    assert s >= 250


def test_custom_objective_and_operators():
    from libpga_tpu.ops.crossover import one_point_crossover
    from libpga_tpu.ops.mutate import make_gaussian_mutate

    pga = PGA(seed=2)
    h = pga.create_population(500, 16)
    pga.set_objective(lambda g: -jnp.sum((g - 0.25) ** 2))
    pga.set_crossover(one_point_crossover)
    pga.set_mutate(make_gaussian_mutate(rate=0.2, sigma=0.05))
    pga.run(40)
    g, s = pga.get_best_with_score(h)
    # random init expectation ≈ -2.33 over 16 genes; near-0 = converged
    assert s > -0.2  # genes near 0.25


def test_get_best_top_sorted():
    pga = PGA(seed=0)
    h = pga.create_population(256, 8)
    pga.set_objective("onemax")
    pga.evaluate(h)
    top = pga.get_best_top(h, 5)
    sums = top.sum(axis=1)
    assert np.all(np.diff(sums) <= 1e-6)  # descending


def test_get_best_all_and_top_all():
    pga = PGA(seed=0)
    h1 = pga.create_population(128, 8)
    h2 = pga.create_population(128, 8)
    pga.set_objective("onemax")
    pga.evaluate_all()
    best = pga.get_best_all()
    b1, s1 = pga.get_best_with_score(h1)
    b2, s2 = pga.get_best_with_score(h2)
    assert best.sum() == pytest.approx(max(s1, s2), abs=1e-4)
    top = pga.get_best_top_all(10)
    assert top.shape == (10, 8)
    sums = top.sum(axis=1)
    assert np.all(np.diff(sums) <= 1e-6)


def test_step_by_step_operator_api():
    """evaluate → crossover → mutate → swap, the reference driver loop."""
    pga = PGA(seed=0)
    h = pga.create_population(256, 16)
    pga.set_objective("onemax")
    before = np.asarray(pga.population(h).genomes).copy()
    for _ in range(5):
        pga.evaluate(h)
        pga.crossover(h)
        pga.mutate(h)
        pga.swap_generations(h)
    pga.evaluate(h)
    after = pga.population(h)
    assert not np.array_equal(before, np.asarray(after.genomes))
    # mean fitness should improve under selection
    assert float(jnp.mean(after.scores)) > float(before.sum(axis=1).mean())


def test_mutate_requires_staged():
    pga = PGA(seed=0)
    h = pga.create_population(16, 4)
    pga.set_objective("onemax")
    with pytest.raises(RuntimeError):
        pga.mutate(h)


def test_migrate_between():
    pga = PGA(seed=0)
    h1 = pga.create_population(64, 8)
    h2 = pga.create_population(64, 8)
    pga.set_objective("onemax")
    pga.evaluate_all()
    best_src = pga.get_best_with_score(h1)[1]
    pga.migrate_between(h1, h2, 0.1)
    # destination now contains source's best
    best_dst = pga.get_best_with_score(h2)[1]
    assert best_dst >= best_src


def test_migrate_random_all():
    pga = PGA(seed=0)
    for _ in range(4):
        pga.create_population(64, 8)
    pga.set_objective("onemax")
    pga.evaluate_all()
    global_best = max(
        pga.get_best_with_score(PopulationHandle(i))[1] for i in range(4)
    )
    pga.migrate(0.1)
    # global best must survive migration (top individuals are copied, and
    # immigrants only replace the destination's worst)
    new_best = max(
        pga.get_best_with_score(PopulationHandle(i))[1] for i in range(4)
    )
    assert new_best >= global_best - 1e-6


def test_c_shaped_api_parity():
    """The pga_* veneer mirrors include/pga.h end to end."""
    p = pga_mod.pga_init(seed=0)
    pop = pga_mod.pga_create_population(p, 200, 8, pga_mod.RANDOM_POPULATION)
    pga_mod.pga_set_objective_function(p, "onemax")
    pga_mod.pga_set_mutate_function(p, None)
    pga_mod.pga_set_crossover_function(p, None)
    pga_mod.pga_run(p, 20)
    g = pga_mod.pga_get_best(p, pop)
    assert g.shape == (8,)
    top = pga_mod.pga_get_best_top(p, pop, 3)
    assert top.shape == (3, 8)
    pga_mod.pga_evaluate(p, pop)
    pga_mod.pga_crossover(p, pop, pga_mod.TOURNAMENT)
    pga_mod.pga_mutate(p, pop)
    pga_mod.pga_swap_generations(p, pop)
    pga_mod.pga_fill_random_values(p, pop)
    pga_mod.pga_deinit(p)


def test_seeded_determinism():
    def run_once():
        pga = PGA(seed=42)
        h = pga.create_population(128, 8)
        pga.set_objective("onemax")
        pga.run(10)
        return np.asarray(pga.population(h).genomes)

    np.testing.assert_array_equal(run_once(), run_once())


def test_metrics_recorded():
    pga = PGA(seed=0)
    pga.create_population(64, 8)
    pga.set_objective("onemax")
    pga.run(5)
    assert pga.metrics.total_generations == 5
    assert pga.metrics.generations_per_sec > 0


def test_run_target_winner_survives():
    """The generation that reaches the target must be the one returned —
    not its offspring (regression: winner used to be bred away)."""
    from libpga_tpu.objectives import onemax_bits

    for seed in range(8):
        pga = PGA(seed=seed)
        h = pga.create_population(200, 16)
        pga.set_objective(onemax_bits)
        gens = pga.run(10_000, target=15.0)
        if gens < 10_000:
            _, s = pga.get_best_with_score(h)
            assert s >= 15.0, f"seed {seed}: claimed target but best={s}"


def test_get_best_top_clamps_k():
    pga = PGA(seed=0)
    h = pga.create_population(32, 8)
    pga.set_objective("onemax")
    pga.evaluate(h)
    top = pga.get_best_top(h, 300)  # k > size must clamp, not crash
    assert top.shape == (32, 8)


def test_migrate_zero_pct_is_noop():
    pga = PGA(seed=0)
    h1 = pga.create_population(64, 8)
    h2 = pga.create_population(64, 8)
    pga.set_objective("onemax")
    pga.evaluate_all()
    before = np.asarray(pga.population(h2).genomes).copy()
    pga.migrate(0.0)
    pga.migrate_between(h1, h2, 0.0)
    np.testing.assert_array_equal(before, np.asarray(pga.population(h2).genomes))
    with pytest.raises(ValueError):
        pga.migrate(1.5)


def test_run_islands_repeat_calls_reuse_cache():
    """Second run_islands call with same shapes must hit the runner cache
    (regression: every call used to rebuild + recompile)."""
    pga = PGA(seed=0)
    for _ in range(4):
        pga.create_population(64, 8)
    pga.set_objective("onemax")
    pga.run_islands(10, 5, 0.1)
    n_cached = len(pga._compiled)
    pga.run_islands(10, 5, 0.1)
    assert len(pga._compiled) == n_cached


class TestValidationMode:
    """PGAConfig(validate=True) — the device-sanitizer stand-in
    (utils/validate.py): clean runs pass; corrupted state is named."""

    def test_clean_run_passes(self):
        from libpga_tpu import PGA, PGAConfig

        pga = PGA(seed=0, config=PGAConfig(validate=True))
        h = pga.create_population(256, 16)
        pga.set_objective("onemax")
        assert pga.run(5) == 5
        pga.evaluate(h)
        pga.crossover(h)
        pga.mutate(h)
        pga.swap_generations(h)
        pga.evaluate(h)

    def test_score_drift_detected(self):
        import dataclasses

        from libpga_tpu import PGA, PGAConfig
        from libpga_tpu.population import Population
        from libpga_tpu.utils.validate import ValidationError

        pga = PGA(seed=0, config=PGAConfig(validate=True))
        h = pga.create_population(256, 16)
        pga.set_objective("onemax")
        pga.run(3)
        pop = pga.population(h)
        # corrupt one stored score: the oracle cross-check must name it
        bad = pop.scores.at[7].add(5.0)
        pga._populations[h.index] = dataclasses.replace(pop, scores=bad)
        with pytest.raises(ValidationError, match="drifted"):
            pga._validate("probe", [0])

    def test_f32_tolerance_catches_centi_scale_drift(self):
        """The oracle atol is dtype-aware: a 0.01-magnitude fused-score
        error on an f32 population (real-bug size — the 100-gene sum's
        ULP is ~1e-5) must be CAUGHT, while the same perturbation on
        bf16 genomes stays inside that dtype's legitimate ~1e-2
        accumulation band."""
        import numpy as np

        from libpga_tpu.objectives import get as get_obj
        from libpga_tpu.utils.validate import (
            ValidationError, check_population,
        )

        rng = np.random.default_rng(3)
        g32 = rng.random((64, 100), dtype=np.float32)
        obj = get_obj("onemax")
        from libpga_tpu.ops.evaluate import evaluate as _evaluate

        import jax.numpy as jnp

        s = np.asarray(_evaluate(obj, jnp.asarray(g32)))
        check_population(obj, jnp.asarray(g32), s, where="probe")  # clean
        bad = s.copy()
        bad[5] += 0.01
        with pytest.raises(ValidationError, match="drifted"):
            check_population(obj, jnp.asarray(g32), bad, where="probe")
        # bf16 genomes: the SAME 0.01 drift is inside the dtype band
        g16 = jnp.asarray(g32).astype(jnp.bfloat16)
        s16 = np.asarray(_evaluate(obj, g16.astype(jnp.float32)))
        bad16 = s16.copy()
        bad16[5] += 0.01
        check_population(obj, g16, bad16, where="probe")

    def test_gene_domain_violation_detected(self):
        import dataclasses

        import jax.numpy as jnp

        from libpga_tpu import PGA, PGAConfig
        from libpga_tpu.utils.validate import ValidationError

        pga = PGA(seed=0, config=PGAConfig(validate=True))
        h = pga.create_population(256, 16)
        pga.set_objective("onemax")
        pga.run(2)
        pop = pga.population(h)
        bad_g = pop.genomes.at[3, 3].set(jnp.float32(jnp.nan))
        pga._populations[h.index] = dataclasses.replace(pop, genomes=bad_g)
        with pytest.raises(ValidationError, match="non-finite"):
            pga._validate("probe", [0])
