"""Checkpoint/resume tests — a capability the reference lacks entirely.

Integrity (ISSUE 5 satellite): every data array carries a CRC32 in the
npz manifest, verified on restore; version mismatches, missing shards,
truncated and bit-flipped files raise a named :class:`CheckpointError`
carrying the offending path instead of a KeyError/zipfile error
mid-merge."""

import os

import numpy as np
import pytest

from libpga_tpu import PGA
from libpga_tpu.engine import PopulationHandle
from libpga_tpu.utils import checkpoint
from libpga_tpu.utils.checkpoint import CheckpointError


def test_save_restore_roundtrip(tmp_path):
    pga = PGA(seed=0)
    h = pga.create_population(64, 8)
    pga.create_population(32, 8)
    pga.set_objective("onemax")
    pga.run(5)
    pga.evaluate_all()
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save(pga, path)

    fresh = PGA(seed=999)
    checkpoint.restore(fresh, path)
    assert fresh.num_populations == 2
    np.testing.assert_array_equal(
        np.asarray(fresh.population(h).genomes),
        np.asarray(pga.population(h).genomes),
    )


def test_bf16_roundtrip(tmp_path):
    """bf16 genomes must survive save/restore: np.savez has no native
    bfloat16 representation, so the checkpoint stores bit patterns plus
    the dtype name (advisor round-1 finding: raw '|V2' saves were
    unrestorable)."""
    import jax.numpy as jnp

    from libpga_tpu import PGAConfig

    pga = PGA(seed=0, config=PGAConfig(gene_dtype=jnp.bfloat16))
    h = pga.create_population(64, 8)
    pga.set_objective("onemax")
    pga.run(3)
    path = str(tmp_path / "ckpt_bf16.npz")
    checkpoint.save(pga, path)

    fresh = PGA(seed=1)
    checkpoint.restore(fresh, path)
    restored = fresh.population(h).genomes
    assert restored.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(restored.astype(jnp.float32)),
        np.asarray(pga.population(h).genomes.astype(jnp.float32)),
    )


def test_shard_pack_merge_roundtrip():
    """The per-process shard format's pack/merge helpers reassemble a
    mesh-sharded array exactly (device shards carry index offsets). On
    this single-process 8-device mesh all shards are addressable, which
    exercises the same code path the two-process smoke drives with
    non-addressable halves."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from libpga_tpu.parallel.mesh import default_mesh
    from libpga_tpu.utils.checkpoint import _merge_array, _pack_array

    mesh = default_mesh()
    arr = jnp.arange(8 * 6 * 4, dtype=jnp.float32).reshape(8, 6, 4)
    sharded = jax.device_put(arr, NamedSharding(mesh, P("islands", None, None)))
    arrays = {}
    _pack_array(arrays, "genomes_0", sharded)
    assert "genomes_0_shard0" in arrays
    merged = _merge_array([arrays], "genomes_0")
    np.testing.assert_array_equal(merged, np.asarray(arr))

    # bf16 shards round-trip through the bit-pattern encoding
    arrays = {}
    _pack_array(arrays, "g", sharded.astype(jnp.bfloat16))
    merged = _merge_array([arrays], "g")
    assert merged.dtype.name == "bfloat16"
    np.testing.assert_array_equal(
        merged.astype(np.float32), np.asarray(arr, dtype=np.float32)
    )

    # a missing shard (simulating a lost process file) must raise
    partial = {k: v for k, v in arrays.items() if "shard7" not in k}
    with np.testing.assert_raises(ValueError):
        _merge_array([partial], "g")


def test_interrupted_save_preserves_previous_checkpoint(tmp_path, monkeypatch):
    """A preemption mid-write must not destroy the previous good
    checkpoint: save() writes a temp file and os.replace()s it into
    place (advisor round-2 finding: direct np.savez truncated the zip)."""
    path = str(tmp_path / "ckpt.npz")

    pga = PGA(seed=0)
    h = pga.create_population(64, 8)
    pga.set_objective("onemax")
    pga.run(3)
    checkpoint.save(pga, path)
    good = np.asarray(pga.population(h).genomes)

    pga.run(3)
    real_savez = np.savez

    def dying_savez(file, **arrays):
        real_savez(file, **{k: v for k, v in list(arrays.items())[:2]})
        raise KeyboardInterrupt  # preempted mid-save

    monkeypatch.setattr(np, "savez", dying_savez)
    try:
        checkpoint.save(pga, path)
    except KeyboardInterrupt:
        pass
    monkeypatch.undo()

    assert not [p for p in tmp_path.iterdir() if ".tmp" in p.name]
    fresh = PGA(seed=1)
    checkpoint.restore(fresh, path)  # previous checkpoint intact
    np.testing.assert_array_equal(
        np.asarray(fresh.population(h).genomes), good
    )


def _write_shard_file(path, proc, n_procs, rows, genomes, scores, keydata,
                      seq=1):
    arrays = {
        "__version__": np.asarray(checkpoint.SHARD_FORMAT_VERSION),
        "__num_populations__": np.asarray(1),
        "__num_processes__": np.asarray(n_procs),
        "__save_seq__": np.asarray(seq),
        "__key__": keydata,
        "genomes_0_shape": np.asarray(genomes.shape, dtype=np.int64),
        "genomes_0_shard0": genomes[rows],
        "genomes_0_shard0_dtype": np.asarray(""),
        "genomes_0_shard0_start": np.asarray([rows.start, 0], dtype=np.int64),
        "scores_0_shape": np.asarray(scores.shape, dtype=np.int64),
        "scores_0_shard0": scores[rows],
        "scores_0_shard0_dtype": np.asarray(""),
        "scores_0_shard0_start": np.asarray([rows.start], dtype=np.int64),
    }
    np.savez(f"{path}.proc{proc}.npz", **arrays)


def test_restore_ignores_stale_wider_shard_files(tmp_path):
    """Shard files left by an earlier run with MORE processes (job
    resized 4 hosts -> 2) must not fail restore: only the file set the
    checkpoint declares is read (advisor round-2 finding)."""
    import jax

    path = str(tmp_path / "ckpt.npz")
    genomes = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)
    scores = np.arange(8, dtype=np.float32)
    keydata = np.asarray(jax.random.key_data(jax.random.key(5)))

    _write_shard_file(path, 0, 2, slice(0, 4), genomes, scores, keydata)
    _write_shard_file(path, 1, 2, slice(4, 8), genomes, scores, keydata)
    # Stale leftovers from the defunct 4-process era, torn seq and all:
    _write_shard_file(path, 2, 4, slice(0, 4), genomes, scores, keydata,
                      seq=999)
    _write_shard_file(path, 3, 4, slice(4, 8), genomes, scores, keydata,
                      seq=998)

    fresh = PGA(seed=1)
    checkpoint.restore(fresh, path)
    np.testing.assert_array_equal(
        np.asarray(fresh.population(PopulationHandle(0)).genomes), genomes
    )
    np.testing.assert_array_equal(
        np.asarray(fresh.population(PopulationHandle(0)).scores), scores
    )


def test_multiprocess_save_leaves_wider_shards_intact(tmp_path, monkeypatch):
    """A multi-process save must NOT delete .proc<k> files from an
    earlier wider run before its own shard set is durably written —
    until every process has saved, those files are part of the only
    restorable checkpoint. restore() ignores them via the declared
    process count instead."""
    import jax

    path = str(tmp_path / "ckpt.npz")
    (tmp_path / "ckpt.npz.proc2.npz").write_bytes(b"old-wide-run")
    (tmp_path / "ckpt.npz.proc3.npz").write_bytes(b"old-wide-run")

    pga = PGA(seed=0)
    pga.create_population(64, 8)
    pga.set_objective("onemax")
    pga.run(2)

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    checkpoint.save(pga, path)

    names = sorted(p.name for p in tmp_path.iterdir())
    assert names == [
        "ckpt.npz.proc0.npz", "ckpt.npz.proc2.npz", "ckpt.npz.proc3.npz"
    ]


def _saved_solver(tmp_path, name="c.npz"):
    path = str(tmp_path / name)
    pga = PGA(seed=0)
    pga.create_population(64, 8)
    pga.set_objective("onemax")
    pga.run(3)
    checkpoint.save(pga, path)
    return pga, path


def test_bit_flipped_array_raises_checkpoint_error(tmp_path):
    """A flipped bit inside an otherwise readable npz must fail the
    per-array CRC32 check with the file named — not restore silently
    corrupted genomes."""
    _, path = _saved_solver(tmp_path)
    data = dict(np.load(path))
    flipped = data["genomes_0"].copy()
    flipped.view(np.uint8)[7] ^= 0x10
    data["genomes_0"] = flipped  # keep the stored crc32: now stale
    np.savez(path, **data)
    with pytest.raises(CheckpointError, match="genomes_0.*corrupted") as ei:
        checkpoint.restore(PGA(seed=1), path)
    assert ei.value.path == path


def test_truncated_file_raises_checkpoint_error(tmp_path):
    _, path = _saved_solver(tmp_path)
    with open(path, "r+b") as fh:
        fh.truncate(os.path.getsize(path) // 2)
    with pytest.raises(CheckpointError, match="unreadable") as ei:
        checkpoint.restore(PGA(seed=1), path)
    assert ei.value.path == path


def test_truncated_shard_file_raises_checkpoint_error(tmp_path):
    """The shard format: one truncated .proc<k> file names ITSELF, so a
    pod operator knows which host's shard to recover."""
    import jax

    path = str(tmp_path / "ckpt.npz")
    genomes = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)
    scores = np.arange(8, dtype=np.float32)
    keydata = np.asarray(jax.random.key_data(jax.random.key(5)))
    _write_shard_file(path, 0, 2, slice(0, 4), genomes, scores, keydata)
    _write_shard_file(path, 1, 2, slice(4, 8), genomes, scores, keydata)
    shard1 = f"{path}.proc1.npz"
    with open(shard1, "r+b") as fh:
        fh.truncate(os.path.getsize(shard1) // 3)
    with pytest.raises(CheckpointError, match="unreadable") as ei:
        checkpoint.restore(PGA(seed=1), path)
    assert ei.value.path == shard1


def test_bit_flipped_shard_raises_checkpoint_error(tmp_path):
    # a 1-process shard set with a corrupted shard payload under a
    # stale (correct-for-the-original) crc
    import jax

    spath = str(tmp_path / "shards.npz")
    genomes = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)
    scores = np.arange(8, dtype=np.float32)
    keydata = np.asarray(jax.random.key_data(jax.random.key(5)))
    _write_shard_file(spath, 0, 1, slice(0, 8), genomes, scores, keydata)
    f0 = f"{spath}.proc0.npz"
    data = dict(np.load(f0))
    good = data["genomes_0_shard0"].copy()
    data["genomes_0_shard0_crc32"] = np.uint32(
        __import__("zlib").crc32(np.ascontiguousarray(good).tobytes())
    )
    bad = good.copy()
    bad.view(np.uint8)[3] ^= 0x01
    data["genomes_0_shard0"] = bad
    np.savez(f0, **data)
    with pytest.raises(CheckpointError, match="corrupted") as ei:
        checkpoint.restore(PGA(seed=1), spath)
    assert ei.value.path == f0


def test_version_mismatch_raises_checkpoint_error(tmp_path):
    _, path = _saved_solver(tmp_path)
    data = dict(np.load(path))
    data["__version__"] = np.asarray(999)
    np.savez(path, **data)
    with pytest.raises(CheckpointError, match="version 999") as ei:
        checkpoint.restore(PGA(seed=1), path)
    assert ei.value.path == path


def test_missing_array_raises_checkpoint_error_not_keyerror(tmp_path):
    """The historical failure shape was a bare KeyError mid-merge; a
    checkpoint declaring 2 populations but carrying 1 must raise the
    named error with the path instead."""
    _, path = _saved_solver(tmp_path)
    data = dict(np.load(path))
    data["__num_populations__"] = np.asarray(2)  # lies: only pop 0 exists
    np.savez(path, **data)
    with pytest.raises(CheckpointError, match="genomes_1") as ei:
        checkpoint.restore(PGA(seed=1), path)
    assert ei.value.path == path


def test_checkpoint_error_is_a_valueerror(tmp_path):
    """Compatibility: callers matching the historical ValueError surface
    keep working."""
    assert issubclass(CheckpointError, ValueError)


def test_crc_recorded_for_every_data_array(tmp_path):
    _, path = _saved_solver(tmp_path)
    with np.load(path) as data:
        keys = set(data.files)
    assert "genomes_0_crc32" in keys and "scores_0_crc32" in keys


def test_pre_crc_checkpoints_still_restore(tmp_path):
    """Forward compatibility: a checkpoint written before the integrity
    manifest (no crc keys) restores unverified, as before."""
    pga, path = _saved_solver(tmp_path)
    data = {
        k: v for k, v in dict(np.load(path)).items()
        if not k.endswith("_crc32")
    }
    np.savez(path, **data)
    fresh = PGA(seed=1)
    checkpoint.restore(fresh, path)
    np.testing.assert_array_equal(
        np.asarray(fresh.population(PopulationHandle(0)).genomes),
        np.asarray(pga.population(PopulationHandle(0)).genomes),
    )


def test_resume_continues_deterministically(tmp_path):
    """save → run(k) must equal restore → run(k): PRNG state round-trips."""
    path = str(tmp_path / "ckpt.npz")

    pga = PGA(seed=7)
    h = pga.create_population(128, 8)
    pga.set_objective("onemax")
    pga.run(5)
    checkpoint.save(pga, path)
    pga.run(5)
    final_a = np.asarray(pga.population(h).genomes)

    pga2 = PGA(seed=123)
    pga2.set_objective("onemax")
    checkpoint.restore(pga2, path)
    pga2.run(5)
    final_b = np.asarray(pga2.population(PopulationHandle(0)).genomes)

    np.testing.assert_array_equal(final_a, final_b)


def test_sigkill_fault_injection_resume(tmp_path):
    """IN-RUN fault injection: a worker process evolving with an
    AutoCheckpointer is SIGKILL'd mid-run (no cleanup, no atexit — the
    preemption the atomic-save design exists for); a fresh process must
    restore the last durable checkpoint and resume to completion."""
    import os
    import signal
    import subprocess
    import sys
    import time as _time

    ckpt = tmp_path / "state.npz"
    marker = tmp_path / "saves.txt"
    worker_src = f"""
import jax
jax.config.update("jax_platforms", "cpu")
from libpga_tpu import PGA, PGAConfig
from libpga_tpu.utils.checkpoint import AutoCheckpointer

pga = PGA(seed=11, config=PGAConfig(mutation_rate=0.05))
for _ in range(4):
    pga.create_population(256, 16)
pga.set_objective("onemax")
ckpt = AutoCheckpointer(pga, {str(ckpt)!r}, every_generations=5)
for i in range(1000):  # far more work than the parent will allow
    pga.run_islands(5, 5, 0.1)
    with open({str(marker)!r}, "a") as f:
        f.write(f"save {{i}}\\n")
"""
    proc = subprocess.Popen(
        [sys.executable, "-c", worker_src],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        # wait until at least two periodic saves are durably on disk,
        # then kill without warning
        deadline = _time.time() + 180
        while _time.time() < deadline:
            if marker.exists() and len(marker.read_text().splitlines()) >= 2:
                break
            if proc.poll() is not None:
                raise AssertionError("worker exited before being killed")
            _time.sleep(0.25)
        else:
            raise AssertionError("worker never reached two checkpoint saves")
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode != 0  # killed, not exited

    # recovery: a fresh solver restores the durable state and resumes
    from libpga_tpu import PGA
    from libpga_tpu.utils import checkpoint

    fresh = PGA(seed=999)
    checkpoint.restore(fresh, str(ckpt))
    assert fresh.num_populations == 4
    fresh.set_objective("onemax")
    best_restored = max(
        fresh.get_best_with_score(h)[1] for h in fresh._handles()
    )
    assert best_restored > 10.0  # progress from before the kill survived
    gens = fresh.run_islands(10, 5, 0.1)
    assert gens == 10  # resumed evolution runs to completion
