"""Checkpoint/resume tests — a capability the reference lacks entirely."""

import numpy as np

from libpga_tpu import PGA
from libpga_tpu.engine import PopulationHandle
from libpga_tpu.utils import checkpoint


def test_save_restore_roundtrip(tmp_path):
    pga = PGA(seed=0)
    h = pga.create_population(64, 8)
    pga.create_population(32, 8)
    pga.set_objective("onemax")
    pga.run(5)
    pga.evaluate_all()
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save(pga, path)

    fresh = PGA(seed=999)
    checkpoint.restore(fresh, path)
    assert fresh.num_populations == 2
    np.testing.assert_array_equal(
        np.asarray(fresh.population(h).genomes),
        np.asarray(pga.population(h).genomes),
    )


def test_bf16_roundtrip(tmp_path):
    """bf16 genomes must survive save/restore: np.savez has no native
    bfloat16 representation, so the checkpoint stores bit patterns plus
    the dtype name (advisor round-1 finding: raw '|V2' saves were
    unrestorable)."""
    import jax.numpy as jnp

    from libpga_tpu import PGAConfig

    pga = PGA(seed=0, config=PGAConfig(gene_dtype=jnp.bfloat16))
    h = pga.create_population(64, 8)
    pga.set_objective("onemax")
    pga.run(3)
    path = str(tmp_path / "ckpt_bf16.npz")
    checkpoint.save(pga, path)

    fresh = PGA(seed=1)
    checkpoint.restore(fresh, path)
    restored = fresh.population(h).genomes
    assert restored.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(restored.astype(jnp.float32)),
        np.asarray(pga.population(h).genomes.astype(jnp.float32)),
    )


def test_shard_pack_merge_roundtrip():
    """The per-process shard format's pack/merge helpers reassemble a
    mesh-sharded array exactly (device shards carry index offsets). On
    this single-process 8-device mesh all shards are addressable, which
    exercises the same code path the two-process smoke drives with
    non-addressable halves."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from libpga_tpu.parallel.mesh import default_mesh
    from libpga_tpu.utils.checkpoint import _merge_array, _pack_array

    mesh = default_mesh()
    arr = jnp.arange(8 * 6 * 4, dtype=jnp.float32).reshape(8, 6, 4)
    sharded = jax.device_put(arr, NamedSharding(mesh, P("islands", None, None)))
    arrays = {}
    _pack_array(arrays, "genomes_0", sharded)
    assert "genomes_0_shard0" in arrays
    merged = _merge_array([arrays], "genomes_0")
    np.testing.assert_array_equal(merged, np.asarray(arr))

    # bf16 shards round-trip through the bit-pattern encoding
    arrays = {}
    _pack_array(arrays, "g", sharded.astype(jnp.bfloat16))
    merged = _merge_array([arrays], "g")
    assert merged.dtype.name == "bfloat16"
    np.testing.assert_array_equal(
        merged.astype(np.float32), np.asarray(arr, dtype=np.float32)
    )

    # a missing shard (simulating a lost process file) must raise
    partial = {k: v for k, v in arrays.items() if "shard7" not in k}
    with np.testing.assert_raises(ValueError):
        _merge_array([partial], "g")


def test_resume_continues_deterministically(tmp_path):
    """save → run(k) must equal restore → run(k): PRNG state round-trips."""
    path = str(tmp_path / "ckpt.npz")

    pga = PGA(seed=7)
    h = pga.create_population(128, 8)
    pga.set_objective("onemax")
    pga.run(5)
    checkpoint.save(pga, path)
    pga.run(5)
    final_a = np.asarray(pga.population(h).genomes)

    pga2 = PGA(seed=123)
    pga2.set_objective("onemax")
    checkpoint.restore(pga2, path)
    pga2.run(5)
    final_b = np.asarray(pga2.population(PopulationHandle(0)).genomes)

    np.testing.assert_array_equal(final_a, final_b)
