"""Checkpoint/resume tests — a capability the reference lacks entirely."""

import numpy as np

from libpga_tpu import PGA
from libpga_tpu.engine import PopulationHandle
from libpga_tpu.utils import checkpoint


def test_save_restore_roundtrip(tmp_path):
    pga = PGA(seed=0)
    h = pga.create_population(64, 8)
    pga.create_population(32, 8)
    pga.set_objective("onemax")
    pga.run(5)
    pga.evaluate_all()
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save(pga, path)

    fresh = PGA(seed=999)
    checkpoint.restore(fresh, path)
    assert fresh.num_populations == 2
    np.testing.assert_array_equal(
        np.asarray(fresh.population(h).genomes),
        np.asarray(pga.population(h).genomes),
    )


def test_bf16_roundtrip(tmp_path):
    """bf16 genomes must survive save/restore: np.savez has no native
    bfloat16 representation, so the checkpoint stores bit patterns plus
    the dtype name (advisor round-1 finding: raw '|V2' saves were
    unrestorable)."""
    import jax.numpy as jnp

    from libpga_tpu import PGAConfig

    pga = PGA(seed=0, config=PGAConfig(gene_dtype=jnp.bfloat16))
    h = pga.create_population(64, 8)
    pga.set_objective("onemax")
    pga.run(3)
    path = str(tmp_path / "ckpt_bf16.npz")
    checkpoint.save(pga, path)

    fresh = PGA(seed=1)
    checkpoint.restore(fresh, path)
    restored = fresh.population(h).genomes
    assert restored.dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(restored.astype(jnp.float32)),
        np.asarray(pga.population(h).genomes.astype(jnp.float32)),
    )


def test_resume_continues_deterministically(tmp_path):
    """save → run(k) must equal restore → run(k): PRNG state round-trips."""
    path = str(tmp_path / "ckpt.npz")

    pga = PGA(seed=7)
    h = pga.create_population(128, 8)
    pga.set_objective("onemax")
    pga.run(5)
    checkpoint.save(pga, path)
    pga.run(5)
    final_a = np.asarray(pga.population(h).genomes)

    pga2 = PGA(seed=123)
    pga2.set_objective("onemax")
    checkpoint.restore(pga2, path)
    pga2.run(5)
    final_b = np.asarray(pga2.population(PopulationHandle(0)).genomes)

    np.testing.assert_array_equal(final_a, final_b)
