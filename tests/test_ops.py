"""Deterministic seeded unit tests per operator (survey §4 plan):
selection pressure, crossover/mutation distribution properties, golden
semantics pinned to fixed PRNG keys."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from libpga_tpu import ops
from libpga_tpu.ops.select import tournament_select, select_parent_pairs
from libpga_tpu.ops.crossover import (
    uniform_crossover,
    one_point_crossover,
    arithmetic_crossover,
    order_preserving_crossover,
)
from libpga_tpu.ops.mutate import point_mutate, gaussian_mutate, swap_mutate
from libpga_tpu.ops.topk import top_k_genomes, best_index
from libpga_tpu.ops.step import make_step


class TestTournamentSelect:
    def test_shapes_and_range(self, key):
        scores = jax.random.normal(key, (100,))
        idx = tournament_select(key, scores, 50, k=2)
        assert idx.shape == (50,)
        assert idx.dtype == jnp.int32
        assert bool(jnp.all((idx >= 0) & (idx < 100)))

    def test_selection_pressure(self, key):
        # Winners' mean score must exceed the population mean — the whole
        # point of tournament selection (reference pga.cu:280-292).
        scores = jnp.arange(1000, dtype=jnp.float32)
        idx = tournament_select(key, scores, 10_000, k=2)
        assert float(jnp.mean(scores[idx])) > float(jnp.mean(scores)) + 50

    def test_larger_k_more_pressure(self, key):
        scores = jnp.arange(1000, dtype=jnp.float32)
        m2 = float(jnp.mean(scores[tournament_select(key, scores, 10_000, k=2)]))
        m8 = float(jnp.mean(scores[tournament_select(key, scores, 10_000, k=8)]))
        assert m8 > m2

    def test_deterministic_under_same_key(self, key):
        scores = jax.random.normal(key, (64,))
        a = tournament_select(key, scores, 32)
        b = tournament_select(key, scores, 32)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_parent_pairs(self, key):
        scores = jnp.arange(10, dtype=jnp.float32)
        p1, p2 = select_parent_pairs(key, scores, 7, k=2)
        assert p1.shape == (7,) and p2.shape == (7,)


class TestSelectionStrategies:
    """Truncation and linear ranking — the strategies the reference's
    placeholder ``crossover_selection_type`` enum (pga.h:37-42) declared
    room for but never implemented."""

    def test_truncation_only_top_fraction(self, key):
        from libpga_tpu.ops.select import truncation_select

        scores = jax.random.uniform(key, (1000,))
        idx = truncation_select(jax.random.fold_in(key, 1), scores, 20_000,
                                tau=0.25)
        picked = np.asarray(scores[idx])
        cutoff = np.quantile(np.asarray(scores), 0.75)
        assert picked.min() >= cutoff - 1e-6  # never below the top quartile
        # uniform within the top quartile: mean ≈ E[U | U > q75] = 0.875
        assert abs(picked.mean() - 0.875) < 0.01

    def test_truncation_param_validation(self, key):
        import pytest

        from libpga_tpu.ops.select import truncation_select

        with pytest.raises(ValueError):
            truncation_select(key, jnp.ones(10), 5, tau=0.0)
        with pytest.raises(ValueError):
            truncation_select(key, jnp.ones(10), 5, tau=1.5)

    def test_linear_rank_pressure(self, key):
        from libpga_tpu.ops.select import linear_rank_select

        scores = jax.random.uniform(key, (1000,))
        # s=2 has tournament-2 intensity: E[winner score] = 2/3 on
        # uniform scores; s→1 approaches uniform selection (mean 1/2).
        i2 = linear_rank_select(jax.random.fold_in(key, 1), scores, 20_000,
                                pressure=2.0)
        i1 = linear_rank_select(jax.random.fold_in(key, 2), scores, 20_000,
                                pressure=1.01)
        m2 = float(jnp.mean(scores[i2]))
        m1 = float(jnp.mean(scores[i1]))
        assert abs(m2 - 2 / 3) < 0.01
        assert abs(m1 - 0.5) < 0.01

    def test_linear_rank_param_validation(self, key):
        import pytest

        from libpga_tpu.ops.select import linear_rank_select

        with pytest.raises(ValueError):
            linear_rank_select(key, jnp.ones(10), 5, pressure=1.0)
        with pytest.raises(ValueError):
            linear_rank_select(key, jnp.ones(10), 5, pressure=2.5)

    def test_select_parent_pairs_kinds(self, key):
        scores = jax.random.uniform(key, (256,))
        for kind in ("truncation", "linear_rank"):
            p1, p2 = select_parent_pairs(key, scores, 64, kind=kind)
            assert p1.shape == (64,) and p2.shape == (64,)
        import pytest

        with pytest.raises(ValueError):
            select_parent_pairs(key, scores, 4, kind="roulette")

    def test_crossover_selection_arg_contract(self, key):
        """PGA.crossover mirrors the C ABI: a non-tournament selection
        argument switches the solver's strategy (default param);
        "tournament" is inert so reference-style per-call passing can't
        clobber a configured strategy; unknown kinds raise without
        mutating state."""
        import pytest

        from libpga_tpu import PGA

        pga = PGA(seed=0)
        h = pga.create_population(256, 8)
        pga.set_objective("onemax")
        pga.evaluate(h)
        pga.crossover(h, "truncation")
        assert pga.config.selection == "truncation"
        pga.crossover(h, "tournament")  # inert: must not clobber
        assert pga.config.selection == "truncation"
        with pytest.raises(ValueError):
            pga.crossover(h, "roulette")
        assert pga.config.selection == "truncation"

    def test_engine_selection_config_end_to_end(self, key):
        """The engine threads config.selection through the XLA run loop:
        a truncation-selection OneMax run must still converge."""
        from libpga_tpu import PGA, PGAConfig

        for kind, param in (("truncation", 0.3), ("linear_rank", 1.8)):
            pga = PGA(seed=0, config=PGAConfig(
                selection=kind, selection_param=param, use_pallas=False,
            ))
            h = pga.create_population(512, 32)
            pga.set_objective("onemax")
            pga.evaluate(h)
            before = float(jnp.mean(pga.population(h).scores))
            pga.run(15)
            after = float(jnp.mean(pga.population(h).scores))
            assert after > before + 1.0, (kind, before, after)


class TestCrossover:
    def test_uniform_matches_reference_semantics(self):
        # rand[i] > 0.5 → take p1, else p2 (reference pga.cu:135-143).
        p1 = jnp.ones(6)
        p2 = jnp.zeros(6)
        rand = jnp.array([0.9, 0.1, 0.51, 0.5, 0.0, 1.0])
        child = uniform_crossover(p1, p2, rand)
        np.testing.assert_array_equal(
            np.asarray(child), [1.0, 0.0, 1.0, 0.0, 0.0, 1.0]
        )

    def test_uniform_mixes_both_parents(self, key):
        p1 = jnp.zeros(1000)
        p2 = jnp.ones(1000)
        rand = jax.random.uniform(key, (1000,))
        child = uniform_crossover(p1, p2, rand)
        frac = float(jnp.mean(child))
        assert 0.4 < frac < 0.6

    def test_one_point(self):
        p1 = jnp.zeros(10)
        p2 = jnp.ones(10)
        rand = jnp.full((10,), 0.5)  # cut at 5
        child = one_point_crossover(p1, p2, rand)
        np.testing.assert_array_equal(np.asarray(child[:5]), np.zeros(5))
        np.testing.assert_array_equal(np.asarray(child[5:]), np.ones(5))

    def test_arithmetic_convex(self, key):
        p1 = jax.random.uniform(key, (32,))
        p2 = jax.random.uniform(jax.random.fold_in(key, 1), (32,))
        rand = jax.random.uniform(jax.random.fold_in(key, 2), (32,))
        child = arithmetic_crossover(p1, p2, rand)
        lo = jnp.minimum(p1, p2) - 1e-6
        hi = jnp.maximum(p1, p2) + 1e-6
        assert bool(jnp.all((child >= lo) & (child <= hi)))

    def test_order_preserving_keeps_unique_cities(self, key):
        # Two valid permutations in, child must not duplicate any city that
        # either parent could supply (reference test3/test.cu:48-64).
        L = 16
        k1, k2, k3 = jax.random.split(key, 3)
        perm1 = jax.random.permutation(k1, L)
        perm2 = jax.random.permutation(k2, L)
        # encode city c as (c + 0.5)/L so int(g*L) decodes exactly
        p1 = (perm1 + 0.5) / L
        p2 = (perm2 + 0.5) / L
        rand = jax.random.uniform(k3, (L,))
        child = order_preserving_crossover(p1, p2, rand)
        cities = np.floor(np.asarray(child) * L).astype(int)
        # Positions that came from a parent (match p1 or p2 gene) must be
        # unique among themselves.
        from_parent = [
            c
            for c, g, g1, g2 in zip(
                cities, np.asarray(child), np.asarray(p1), np.asarray(p2)
            )
            if g == g1 or g == g2
        ]
        assert len(from_parent) == len(set(from_parent))

    def test_order_preserving_identical_parents(self):
        L = 8
        perm = jnp.arange(L)
        p = (perm + 0.5) / L
        rand = jnp.zeros(L)
        child = order_preserving_crossover(p, p, rand)
        np.testing.assert_allclose(np.asarray(child), np.asarray(p))

    def test_order_preserving_batched_matches_scan(self, key):
        """The gather-free batched formulation (the one the engine's breed
        actually runs — operator protocol ``.batched``) must be
        bit-identical to the per-row scan reference across random
        inputs, including non-permutation parents."""
        from libpga_tpu.ops.crossover import _order_preserving_batched

        P, L = 48, 37
        k1, k2, k3 = jax.random.split(key, 3)
        p1 = jax.random.uniform(k1, (P, L))
        p2 = jax.random.uniform(k2, (P, L))
        rand = jax.random.uniform(k3, (P, L))
        a = jax.vmap(order_preserving_crossover)(p1, p2, rand)
        b = _order_preserving_batched(p1, p2, rand)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert order_preserving_crossover.batched is _order_preserving_batched


class TestMutate:
    def test_point_mutate_fires(self):
        g = jnp.zeros(10)
        # rand[1] <= rate → fire; position floor(rand[0]*L)=3; value rand[2]
        rand = jnp.zeros(10).at[0].set(0.35).at[1].set(0.0).at[2].set(0.77)
        out = point_mutate(g, rand, rate=0.01)
        assert out[3] == pytest.approx(0.77)
        assert float(jnp.sum(out != 0)) == 1

    def test_point_mutate_holds_fire(self):
        g = jnp.zeros(10)
        rand = jnp.zeros(10).at[1].set(0.5).at[2].set(0.77)
        out = point_mutate(g, rand, rate=0.01)
        np.testing.assert_array_equal(np.asarray(out), np.zeros(10))

    def test_point_mutate_rate_statistics(self, key):
        P, L = 20_000, 8
        genomes = jnp.zeros((P, L))
        rand = jax.random.uniform(key, (P, L))
        out = jax.vmap(lambda g, r: point_mutate(g, r, rate=0.01))(genomes, rand)
        changed = float(jnp.mean(jnp.any(out != 0, axis=1)))
        assert 0.005 < changed < 0.02  # ~1% of individuals mutate

    def test_gaussian_mutate_bounds(self, key):
        g = jax.random.uniform(key, (64,))
        rand = jax.random.uniform(jax.random.fold_in(key, 1), (64,))
        out = gaussian_mutate(g, rand, rate=1.0, sigma=5.0)
        assert bool(jnp.all((out >= 0.0) & (out < 1.0)))

    def test_swap_mutate_is_permutation(self):
        g = jnp.arange(10, dtype=jnp.float32) / 10
        rand = jnp.zeros(10).at[0].set(0.25).at[1].set(0.85).at[2].set(0.0)
        out = swap_mutate(g, rand, rate=0.5)
        assert sorted(np.asarray(out).tolist()) == sorted(np.asarray(g).tolist())
        assert out[2] == g[8] and out[8] == g[2]


class TestTopK:
    def test_top_k(self, key):
        genomes = jax.random.uniform(key, (100, 4))
        scores = jnp.arange(100, dtype=jnp.float32)
        g, s = top_k_genomes(genomes, scores, 3)
        np.testing.assert_array_equal(np.asarray(s), [99.0, 98.0, 97.0])
        np.testing.assert_allclose(np.asarray(g[0]), np.asarray(genomes[99]))

    def test_best_index(self):
        scores = jnp.array([1.0, 5.0, 3.0])
        assert int(best_index(scores)) == 1


class TestStep:
    def test_step_shapes_and_purity(self, key):
        from libpga_tpu.ops.mutate import make_point_mutate

        step = make_step(
            lambda g: jnp.sum(g), uniform_crossover, make_point_mutate(0.01)
        )
        genomes = jax.random.uniform(key, (128, 16))
        g2, scores = jax.jit(step)(genomes, jax.random.fold_in(key, 1))
        assert g2.shape == genomes.shape
        assert scores.shape == (128,)
        # Same key → identical result (pure function).
        g3, _ = jax.jit(step)(genomes, jax.random.fold_in(key, 1))
        np.testing.assert_array_equal(np.asarray(g2), np.asarray(g3))

    def test_step_scores_describe_returned_genomes(self, key):
        """Round-2 verdict finding: step's returned scores must be the
        NEXT generation's fitness, not the input generation's."""
        from libpga_tpu.ops.evaluate import evaluate
        from libpga_tpu.ops.mutate import make_point_mutate

        obj = lambda g: jnp.sum(g)
        step = jax.jit(make_step(obj, uniform_crossover, make_point_mutate(0.2)))
        genomes = jax.random.uniform(key, (128, 16))
        g2, scores = step(genomes, jax.random.fold_in(key, 1))
        np.testing.assert_allclose(
            np.asarray(scores), np.asarray(evaluate(obj, g2)), rtol=1e-6
        )
        # Threading the returned scores back in skips the re-evaluation
        # and must give the identical generation.
        g3a, s3a = step(g2, jax.random.fold_in(key, 2))
        g3b, s3b = step(g2, jax.random.fold_in(key, 2), scores)
        np.testing.assert_array_equal(np.asarray(g3a), np.asarray(g3b))
        np.testing.assert_array_equal(np.asarray(s3a), np.asarray(s3b))

    def test_step_improves_onemax(self, key):
        from libpga_tpu.ops.mutate import make_point_mutate

        step = jax.jit(
            make_step(
                lambda g: jnp.sum(g), uniform_crossover, make_point_mutate(0.01)
            )
        )
        genomes = jax.random.uniform(key, (512, 32))
        first_mean = float(jnp.mean(jnp.sum(genomes, axis=1)))
        k = key
        for i in range(20):
            k, sub = jax.random.split(k)
            genomes, scores = step(genomes, sub)
        last_mean = float(jnp.mean(jnp.sum(genomes, axis=1)))
        assert last_mean > first_mean + 2.0

    def test_elitism_preserves_best(self, key):
        from libpga_tpu.ops.mutate import make_point_mutate

        obj = lambda g: jnp.sum(g)
        step = jax.jit(
            make_step(obj, uniform_crossover, make_point_mutate(0.5), elitism=4)
        )
        genomes = jax.random.uniform(key, (64, 8))
        best_before = float(jnp.max(jnp.sum(genomes, axis=1)))
        g2, _ = step(genomes, jax.random.fold_in(key, 1))
        best_after = float(jnp.max(jnp.sum(g2, axis=1)))
        assert best_after >= best_before - 1e-5


class TestRegressionFindings:
    def test_gaussian_mutate_sign_balance(self, key):
        # The fire gate must be independent of the Box-Muller angle: at low
        # rates both positive AND negative deltas must occur.
        g = jnp.full((4096,), 0.5)
        rand = jax.random.uniform(key, (4096,))
        out = gaussian_mutate(g, rand, rate=0.1, sigma=0.1)
        delta = np.asarray(out - g)
        fired = delta[delta != 0]
        assert len(fired) > 100
        pos = (fired > 0).mean()
        assert 0.3 < pos < 0.7  # roughly symmetric noise
