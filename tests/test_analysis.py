"""The invariant guard itself (``libpga_tpu/analysis``, ISSUE 13).

Four property families:

1. **Lint rules** — every rule fires on its positive fixture (at the
   expected sites) and is silent on its negative fixture; the
   suppression machinery silences scoped violations and reports stale
   directives; the REAL repo tree lints clean (the acceptance gate —
   a rule that cries wolf on the shipped code is a broken rule).
2. **IR auditor** — ``fingerprint`` is name-insensitive (two
   differently named replicas of one program fingerprint equal),
   order-sensitive (a real structural change fingerprints different),
   and stable across two fresh processes at a fixed seed;
   ``collective_budget`` reproduces the 1-ppermute + 1-all_gather gate
   on the real pop_shards=4 lowering and rejects wrong budgets;
   ``donation_check`` / ``callback_free`` pass and fail where they
   should.
3. **ABI cross-checker** — the repo's 3-way ABI is in sync, and
   deliberately injected drift (format-string arity, renamed bridge
   function, broken snapshot shape, undeclared driver symbol) is
   caught with file:line findings.
4. **Runner** — ``tools/lint_pga.py`` exits 0 on the clean tree and
   nonzero with diagnostics when handed a violating file.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from libpga_tpu.analysis import (
    IRContractError,
    callback_free,
    canonical_text,
    check_abi,
    check_repo_abi,
    collective_budget,
    donation_check,
    fingerprint,
    lint_file,
    lint_paths,
)
from libpga_tpu.analysis import lint as lint_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "lint")


def fixture(name):
    return os.path.join(FIXTURES, name)


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------------- lint rules


class TestLintRules:
    @pytest.mark.parametrize("rule,bad,good", [
        ("spool-atomic-write", "spool_atomic_write_bad.py",
         "spool_atomic_write_good.py"),
        ("event-kind-registered", "event_kind_bad.py",
         "event_kind_good.py"),
        ("no-wallclock-in-traced", "wallclock_bad.py",
         "wallclock_good.py"),
        ("lock-guarded-registry", "lock_registry_bad.py",
         "lock_registry_good.py"),
        ("ring-framed-write", "ring_framed_write_bad.py",
         "ring_framed_write_good.py"),
    ])
    def test_rule_fires_on_bad_and_is_silent_on_good(
        self, rule, bad, good
    ):
        bad_findings = lint_file(fixture(bad))
        assert rules_of(bad_findings) == [rule], bad_findings
        assert len(bad_findings) >= 2  # each bad fixture has >1 site
        assert lint_file(fixture(good)) == []

    def test_spool_rule_names_both_write_shapes(self):
        messages = [f.message for f in lint_file(
            fixture("spool_atomic_write_bad.py")
        )]
        assert any("open" in m for m in messages)
        assert any("savez" in m for m in messages)

    def test_wallclock_rule_reports_transitive_reach(self):
        findings = lint_file(fixture("wallclock_bad.py"))
        lines = {f.line for f in findings}
        # direct while_loop body, jitted scorer, AND the helper reached
        # through the call-graph walk
        assert len(lines) == 3, findings
        assert any("time.monotonic" in f.message for f in findings)
        assert any("np.random" in f.message for f in findings)

    def test_event_rule_reports_missing_required_field(self):
        findings = lint_file(fixture("event_kind_bad.py"))
        assert any("pbt_epohc" in f.message for f in findings)
        assert any("required field" in f.message for f in findings)

    def test_suppression_silences_and_unused_is_reported(self):
        assert lint_file(fixture("suppressed_ok.py")) == []
        findings = lint_file(fixture("suppressed_unused.py"))
        assert rules_of(findings) == ["unused-suppression"]

    def test_clean_tree(self):
        """THE acceptance gate: every rule silent on the shipped code
        (fixed findings fixed, genuine false positives suppressed with
        rationale)."""
        findings = lint_paths(lint_mod.default_paths(REPO))
        assert findings == [], "\n".join(str(f) for f in findings)

    def test_event_fields_parse_matches_live_module(self):
        """The AST-extracted schema (lint fast path, no jax import)
        is the live EVENT_FIELDS dict, byte for byte."""
        from libpga_tpu.utils.telemetry import EVENT_FIELDS

        parsed = lint_mod.load_event_fields(REPO)
        assert parsed == {k: tuple(v) for k, v in EVENT_FIELDS.items()}


# -------------------------------------------------------------- IR audit


def _mini_engine(**cfg):
    from libpga_tpu import PGA, PGAConfig

    pga = PGA(seed=0, config=PGAConfig(use_pallas=False, **cfg))
    pga.create_population(64, 16)
    pga.set_objective("onemax")
    pop = pga._populations[0]
    args = (
        pop.genomes, jax.random.key(0), jnp.int32(3),
        jnp.float32(jnp.inf), pga._mutate_params(),
    )
    return pga._compiled_run(64, 16), args


class TestFingerprint:
    def test_name_insensitive_structure_sensitive(self):
        def f(x, y):
            return x * 2.0 + y

        def g(x, y):  # same program, different name
            return x * 2.0 + y

        def h(x, y):  # different program
            return x * 3.0 + y

        a = jnp.ones((8, 4))
        assert fingerprint(f, a, a) == fingerprint(g, a, a)
        assert fingerprint(f, a, a) != fingerprint(h, a, a)

    def test_accepts_jitted_and_shape_structs(self):
        def f(x):
            return x + 1.0

        s = jax.ShapeDtypeStruct((4,), jnp.float32)
        assert fingerprint(jax.jit(f), s) == fingerprint(f, s)

    def test_stable_across_two_processes_at_fixed_seed(self):
        """Two fresh interpreters lower the same tiny engine run and
        must agree on the digest — the property that lets fingerprints
        gate CI across workers."""
        prog = (
            "import jax, jax.numpy as jnp\n"
            "jax.config.update('jax_threefry_partitionable', True)\n"
            "from libpga_tpu import PGA, PGAConfig\n"
            "from libpga_tpu.analysis import fingerprint\n"
            "pga = PGA(seed=3, config=PGAConfig(use_pallas=False))\n"
            "pga.create_population(64, 16)\n"
            "pga.set_objective('onemax')\n"
            "pop = pga._populations[0]\n"
            "args = (pop.genomes, jax.random.key(0), jnp.int32(3),\n"
            "        jnp.float32(jnp.inf), pga._mutate_params())\n"
            "print(fingerprint(pga._compiled_run(64, 16), *args))\n"
        )
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            # bit-identity across processes needs the partitionable
            # threefry choice pinned in the children (the conftest
            # sets it in-process only)
            "JAX_THREEFRY_PARTITIONABLE": "true",
        }
        digests = []
        for _ in range(2):
            out = subprocess.run(
                [sys.executable, "-c", prog], capture_output=True,
                text=True, env=env, cwd=REPO, timeout=300,
            )
            assert out.returncode == 0, out.stderr[-2000:]
            digests.append(out.stdout.strip().splitlines()[-1])
        assert digests[0] == digests[1]
        assert len(digests[0]) == 64  # sha256 hex


class TestIRContracts:
    def test_donation_check_passes_on_engine_and_fails_undonated(self):
        fn, args = _mini_engine()
        assert donation_check(fn, *args) >= 1

        def f(x):
            return x + 1.0

        with pytest.raises(IRContractError, match="donated"):
            donation_check(f, jnp.ones((4,)))

    def test_callback_free_detects_pure_callback(self):
        fn, args = _mini_engine()
        callback_free(fn, *args)  # the real loop is clean

        def cb(x):
            return jax.pure_callback(
                lambda v: np.asarray(v) * 2,
                jax.ShapeDtypeStruct((4,), jnp.float32), x,
            )

        with pytest.raises(IRContractError, match="pure_callback"):
            callback_free(cb, jnp.ones((4,), jnp.float32))

    @pytest.mark.skipif(
        len(jax.devices()) < 8, reason="needs the 8-device CPU harness"
    )
    def test_collective_budget_on_real_sharded_lowering(self):
        from libpga_tpu import PGA, PGAConfig

        pga = PGA(seed=7, config=PGAConfig(
            pop_shards=4, selection="truncation", mutation_rate=0.05,
            use_pallas=False,
        ))
        pga.create_population(256, 32)
        pga.set_objective("onemax_bits")
        fn = pga._compiled_sharded_run(256, 32)
        pop = pga._populations[0]
        keys = jax.random.split(jax.random.key(0), 4)
        args = (
            pop.genomes, keys, jnp.int32(3), jnp.float32(jnp.inf),
            pga._mutate_params(),
        )
        counts = collective_budget(
            fn.jitted, *args, ppermute=1, all_gather=1
        )
        assert counts["ppermute"] == 1 and counts["all_gather"] == 1
        with pytest.raises(IRContractError, match="all_gather"):
            collective_budget(fn.jitted, *args, ppermute=1, all_gather=2)

    def test_while_body_scope_requires_a_fused_loop(self):
        def flat(x):
            return x * 2.0

        with pytest.raises(IRContractError, match="while"):
            collective_budget(
                flat, jnp.ones((4,)), ppermute=0, all_gather=0
            )

    def test_canonical_text_keeps_everything_but_the_module_id(self):
        def f(x):
            return x + 1.0

        text = canonical_text(f, jnp.ones((4,)))
        assert text.startswith("module @jit__canonical")
        assert "stablehlo.add" in text


# ------------------------------------------------------------- ABI check


class TestABICheck:
    def test_repo_abi_in_sync(self):
        findings = check_repo_abi(REPO)
        assert findings == [], "\n".join(str(f) for f in findings)

    def _paths(self):
        return (
            os.path.join(REPO, "capi", "pga_tpu.h"),
            os.path.join(REPO, "capi", "pga_tpu.cc"),
            os.path.join(REPO, "libpga_tpu", "capi_bridge.py"),
        )

    def test_injected_format_arity_drift_is_caught(self, tmp_path):
        header, cc, bridge = self._paths()
        bad = str(tmp_path / "pga_tpu.cc")
        with open(cc) as fh:
            text = fh.read()
        assert 'call_long("set_pop_shards", "(lI)"' in text
        with open(bad, "w") as fh:
            fh.write(text.replace(
                'call_long("set_pop_shards", "(lI)"',
                'call_long("set_pop_shards", "(lII)"', 1,
            ))
        findings = check_abi(header, bad, bridge)
        assert len(findings) == 1
        assert "signature drift" in findings[0].message
        assert "set_pop_shards" in findings[0].message
        assert findings[0].line > 0

    def test_injected_bridge_signature_drift_is_caught(self, tmp_path):
        """The acceptance scenario: a parameter added on the Python
        side without touching the .cc marshal."""
        header, cc, bridge = self._paths()
        bad = str(tmp_path / "capi_bridge.py")
        with open(bridge) as fh:
            text = fh.read()
        assert "def set_telemetry(handle: int, max_gens: int)" in text
        with open(bad, "w") as fh:
            fh.write(text.replace(
                "def set_telemetry(handle: int, max_gens: int)",
                "def set_telemetry(handle: int, max_gens: int, "
                "flush: bool)", 1,
            ))
        findings = check_abi(header, cc, bad)
        assert any(
            "set_telemetry" in f.message and "drift" in f.message
            for f in findings
        ), findings

    def test_injected_missing_definition_is_caught(self, tmp_path):
        header, cc, bridge = self._paths()
        bad = str(tmp_path / "pga_tpu.h")
        with open(header) as fh:
            text = fh.read()
        with open(bad, "w") as fh:
            fh.write(text + "\nint pga_totally_new(int x);\n")
        findings = check_abi(bad, cc, bridge)
        assert any(
            "pga_totally_new" in f.message and "no definition" in f.message
            for f in findings
        )

    def test_snapshot_shape_contract_is_enforced(self, tmp_path):
        header, cc, bridge = self._paths()
        bad = str(tmp_path / "pga_tpu.h")
        with open(header) as fh:
            text = fh.read()
        needle = "long pga_session_snapshot(char *buf, unsigned long cap);"
        assert needle in text
        with open(bad, "w") as fh:
            fh.write(text.replace(
                needle, "int pga_session_snapshot(char *buf, int cap);", 1
            ))
        findings = check_abi(bad, cc, bridge)
        assert any("retry-once" in f.message for f in findings)

    def test_driver_symbol_coverage(self, tmp_path):
        header, cc, bridge = self._paths()
        driver = str(tmp_path / "driver.c")
        with open(driver, "w") as fh:
            fh.write("int main(void){ return pga_not_an_api(0); }\n")
        findings = check_abi(header, cc, bridge, driver_paths=(driver,))
        assert any("pga_not_an_api" in f.message for f in findings)


# ---------------------------------------------------------------- runner


class TestRunner:
    def test_runner_clean_tree_exits_zero(self):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "lint_pga.py"),
             "--lint", "--abi"],
            capture_output=True, text=True, cwd=REPO, timeout=300,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert "clean" in out.stdout

    def test_runner_reports_violations_with_file_line(self):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "lint_pga.py"),
             fixture("spool_atomic_write_bad.py")],
            capture_output=True, text=True, cwd=REPO, timeout=300,
        )
        assert out.returncode == 1
        assert "spool_atomic_write_bad.py:15" in out.stdout
        assert "[spool-atomic-write]" in out.stdout

    def test_runner_changed_mode_runs(self):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "lint_pga.py"),
             "--changed"],
            capture_output=True, text=True, cwd=REPO, timeout=300,
        )
        # whatever the working tree's state, --changed must complete
        # and keep the file:line discipline on anything it reports
        assert out.returncode in (0, 1), out.stdout + out.stderr
        if out.returncode == 1:
            assert ": [" in out.stdout
