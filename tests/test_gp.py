"""Tree-based genetic programming subsystem (ISSUE 11).

Covers the acceptance gates:

- the stack-machine evaluator (XLA interpreter AND interpret-mode
  Pallas kernel) agrees with the pure-numpy reference interpreter on
  randomized well-formed postfix programs, on max-stack-depth and
  constant-only edge cases, and on ARBITRARY gene matrices (skip-rule
  totality);
- size-fair subtree crossover and subtree/point mutation provably
  preserve strict postfix well-formedness for all admissible genome
  pairs (randomized property test over encodings), and never exceed
  the token capacity;
- GP runs compose with ``pop_shards > 1`` bit-identically (final
  best) with single-shard same-seed runs;
- GP requests batch-serve bit-identically to the engine path, in
  their own shape buckets;
- the tuning space exposes a >1-plan GP knob space ON CPU, the SR
  reverse-registry name derives tuning-DB keys without colliding with
  builtin objective names, and resolution precedence holds;
- vector-genome engines lower BYTE-IDENTICAL StableHLO with the GP
  subsystem imported and exercised (structural guard).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from libpga_tpu import PGA, GPConfig, PGAConfig, TelemetryConfig
from libpga_tpu.gp import encoding as enc
from libpga_tpu.gp import operators as gpo
from libpga_tpu.gp.interpreter import make_eval_rows, stack_predict
from libpga_tpu.gp.reference import reference_predict, reference_scores
from libpga_tpu.gp.sr import make_dataset, symbolic_regression

GP = GPConfig(max_nodes=10, n_vars=2)
SMALL = GPConfig(
    max_nodes=8, n_vars=2, consts=(1.0, 2.0), unary=("neg",),
    binary=("add", "sub", "mul"),
)
CONFIGS = [
    GP,
    SMALL,
    GPConfig(max_nodes=12, n_vars=3, unary=(), binary=("add", "mul")),
    GPConfig(max_nodes=6, n_vars=1, consts=()),
]


def _rand_pop(gp, n, seed=0):
    return enc.random_population(jax.random.key(seed), n, gp)


def _dataset(gp, n=24, seed=0):
    return make_dataset(
        lambda *xs: xs[0] * xs[-1] + xs[0],
        n_samples=n, n_vars=gp.n_vars, seed=seed,
    )


# ------------------------------------------------------------- encoding


class TestEncoding:
    def test_roundtrip_and_render(self):
        g = enc.encode_program(
            [("var", 0), ("var", 1), "mul", ("var", 0), "add"], GP
        )
        assert enc.is_well_formed(g, GP)
        assert enc.program_length(g, GP) == 5
        assert enc.decode_expression(g, GP) == "((x0 * x1) + x0)"

    def test_opcode_table_layout(self):
        names = GP.op_names()
        assert names[0] == "pad" and names[1] == "var"
        assert len(names) == len(GP.op_arities())
        no_const = GPConfig(max_nodes=6, consts=())
        assert "const" not in no_const.op_names()

    @pytest.mark.parametrize("gp", CONFIGS)
    def test_random_programs_well_formed(self, gp):
        pop = np.asarray(_rand_pop(gp, 128, seed=3))
        assert all(enc.is_well_formed(r, gp) for r in pop)
        lengths = [enc.program_length(r, gp) for r in pop]
        assert max(lengths) <= gp.max_nodes
        assert min(lengths) >= 1

    def test_no_unary_grow_yields_odd_lengths(self):
        gp = CONFIGS[2]
        assert not gp.unary
        pop = np.asarray(_rand_pop(gp, 64, seed=5))
        assert all(enc.program_length(r, gp) % 2 == 1 for r in pop)

    def test_structure_spans_match_bruteforce(self):
        gp = SMALL
        pop = _rand_pop(gp, 32, seed=9)
        st = enc.program_structure(pop, gp)
        arr = np.asarray(pop)
        ops = np.clip(
            np.floor(arr[:, 0::2] * gp.n_ops).astype(int), 0, gp.n_ops - 1
        )
        arity = np.asarray(gp.op_arities())
        for p in range(arr.shape[0]):
            n = enc.program_length(arr[p], gp)
            for i in range(n):
                # brute force: walk back until the slice's net stack
                # effect is exactly +1 (a complete subtree).
                need = 1
                j = i
                while True:
                    need += arity[ops[p, j]] - 1
                    if need == 0:
                        break
                    j -= 1
                assert int(st.span[p, i]) == i - j + 1
                assert int(st.start[p, i]) == j

    def test_canonicalize_preserves_semantics_and_idempotent(self):
        gp = GP
        X, _ = _dataset(gp)
        rnd = np.random.default_rng(2).uniform(
            0, 1, (64, gp.genome_len)
        ).astype(np.float32)
        canon = np.asarray(enc.canonicalize(jnp.asarray(rnd), gp))
        a = reference_predict(rnd, X, gp)
        b = reference_predict(canon, X, gp)
        assert np.allclose(a, b, rtol=1e-6, atol=1e-6, equal_nan=True)
        twice = np.asarray(enc.canonicalize(jnp.asarray(canon), gp))
        assert np.array_equal(canon, twice)

    def test_config_validation(self):
        with pytest.raises(ValueError):
            GPConfig(max_nodes=1)
        with pytest.raises(ValueError):
            GPConfig(unary=("nope",))
        with pytest.raises(ValueError):
            GPConfig(max_nodes=10, opcode_block=3)


# ----------------------------------------------------------- evaluators


class TestInterpreter:
    @pytest.mark.parametrize("gp", CONFIGS)
    def test_matches_reference_on_well_formed(self, gp):
        X, _ = _dataset(gp)
        pop = _rand_pop(gp, 96, seed=11)
        got = np.asarray(stack_predict(pop, jnp.asarray(X.T), gp))
        want = reference_predict(np.asarray(pop), X, gp)
        assert np.allclose(got, want, rtol=1e-5, atol=1e-5, equal_nan=True)

    def test_matches_reference_on_arbitrary_genomes(self):
        gp = GP
        X, _ = _dataset(gp)
        rnd = np.random.default_rng(7).uniform(
            0, 1, (64, gp.genome_len)
        ).astype(np.float32)
        got = np.asarray(stack_predict(jnp.asarray(rnd), jnp.asarray(X.T), gp))
        want = reference_predict(rnd, X, gp)
        assert np.allclose(got, want, rtol=1e-5, atol=1e-5, equal_nan=True)

    def test_max_depth_and_constant_only_edges(self):
        gp = SMALL
        X, _ = _dataset(gp)
        # Max stack pressure: T//2 terminals then binary reductions —
        # the deepest profile a strictly well-formed program of this
        # capacity reaches (2k-1 tokens, peak depth k).
        k = gp.max_nodes // 2
        toks = [("var", 0)] * k + ["add"] * (k - 1)
        deep = enc.encode_program(toks, gp)
        assert enc.is_well_formed(deep, gp)
        const_only = enc.encode_program([("const", 1)], gp)
        empty = np.full(gp.genome_len, gp.pad_gene, np.float32)
        batch = jnp.asarray(np.stack([deep, const_only, empty]))
        got = np.asarray(stack_predict(batch, jnp.asarray(X.T), gp))
        want = reference_predict(np.asarray(batch), X, gp)
        assert np.allclose(got, want, rtol=1e-6, atol=1e-6)
        assert np.allclose(got[1], 2.0)  # consts[1]
        assert np.all(got[2] == 0.0)  # empty program reads 0

    def test_scores_sanitize_nonfinite(self):
        gp = GPConfig(max_nodes=8, n_vars=1, unary=("exp",),
                      binary=("mul", "add"))
        X = np.full((8, 1), 80.0, np.float32)  # exp(80) overflows f32
        y = np.zeros(8, np.float32)
        prog = enc.encode_program(
            [("var", 0), "exp", "exp"], gp
        )
        rows = make_eval_rows(gp, X, y)
        s = np.asarray(rows(jnp.asarray(prog[None, :])))
        assert s[0] == -np.inf  # sanitized, not NaN
        ref = reference_scores(prog[None, :], X, y, gp)
        assert ref[0] == -np.inf

    def test_knobs_change_plan_not_semantics(self):
        gp = GP
        X, y = _dataset(gp)
        pop = _rand_pop(gp, 32, seed=1)
        base = np.asarray(make_eval_rows(gp, X, y)(pop))
        for S, B in ((32, 1), (16, 5), (64, 2)):
            if gp.max_nodes % B:
                continue
            got = np.asarray(
                make_eval_rows(gp, X, y, stack_depth=S, opcode_block=B)(pop)
            )
            assert np.allclose(base, got, rtol=1e-6, atol=1e-6)

    def test_invalid_knobs_raise(self):
        gp = GP
        X, y = _dataset(gp)
        with pytest.raises(ValueError):
            make_eval_rows(gp, X, y, stack_depth=4)(_rand_pop(gp, 4))
        with pytest.raises(ValueError):
            make_eval_rows(gp, X, y, opcode_block=3)(_rand_pop(gp, 4))


class TestFusedKernel:
    def test_plan_resolution_and_gates(self):
        from libpga_tpu.ops.gp_eval import GP_ROW_POOL, gp_eval_plan

        gp = GPConfig(max_nodes=16, n_vars=2)
        plan = gp_eval_plan(256, gp, 48)
        assert plan["path"] == "fused"
        assert plan["stack_depth"] == 16 and plan["opcode_block"] == 1
        assert plan["rows_per_block"] in GP_ROW_POOL
        assert plan["grid"] * plan["rows_per_block"] == 256
        with pytest.raises(ValueError):
            gp_eval_plan(256, gp, 48, stack_depth=8)
        with pytest.raises(ValueError):
            gp_eval_plan(256, gp, 48, opcode_block=3)
        # A pop no pool entry divides: the XLA interpreter serves.
        assert gp_eval_plan(100, gp, 48)["path"] == "xla"

    def test_fused_agrees_with_interpreter(self):
        from jax.experimental.pallas import tpu as pltpu

        from libpga_tpu.ops.gp_eval import make_gp_eval

        gp = GPConfig(max_nodes=16, n_vars=2)
        X, y = make_dataset(
            lambda a, b: a * b + a, n_samples=48, n_vars=2
        )
        pop = enc.random_population(jax.random.key(0), 128, gp)
        want = np.asarray(make_eval_rows(gp, X, y)(pop))
        with pltpu.force_tpu_interpret_mode():
            for kw in ({}, {"stack_depth": 32, "opcode_block": 4}):
                got = np.asarray(make_gp_eval(gp, X, y, pop=128, **kw)(pop))
                assert np.allclose(want, got, rtol=1e-5, atol=1e-5), kw


# ------------------------------------------------------------ operators


class TestOperators:
    @pytest.mark.parametrize("gp", CONFIGS)
    def test_crossover_closure_property(self, gp):
        xo = gpo.make_subtree_crossover(gp)
        pop = _rand_pop(gp, 200, seed=21)
        perm = jax.random.permutation(jax.random.key(22), 200)
        rand = jax.random.uniform(jax.random.key(23), (200, xo.rand_cols))
        kids = np.asarray(xo.batched(pop, pop[perm], rand))
        assert all(enc.is_well_formed(r, gp) for r in kids)
        assert max(enc.program_length(r, gp) for r in kids) <= gp.max_nodes

    @pytest.mark.parametrize("gp", CONFIGS)
    def test_mutation_closure_property(self, gp):
        pop = _rand_pop(gp, 200, seed=31)
        for make in (
            lambda: gpo.make_subtree_mutate(gp, rate=0.9),
            lambda: gpo.make_gp_point_mutate(gp, rate=0.9),
            lambda: gpo.make_gp_mutate(gp, 0.7, 0.7),
        ):
            op = make()
            rand = jax.random.uniform(
                jax.random.key(32), (200, op.rand_cols)
            )
            out = np.asarray(op.batched(pop, rand))
            assert all(enc.is_well_formed(r, gp) for r in out)

    def test_operators_total_on_arbitrary_genomes(self):
        gp = GP
        rnd = jnp.asarray(np.random.default_rng(5).uniform(
            0, 1, (64, gp.genome_len)
        ).astype(np.float32))
        xo = gpo.make_subtree_crossover(gp)
        kids = xo.batched(
            rnd, _rand_pop(gp, 64),
            jax.random.uniform(jax.random.key(0), (64, 2)),
        )
        assert np.isfinite(np.asarray(kids)).all()  # total, no crash
        X, _ = _dataset(gp)
        # children still evaluate identically under both interpreters
        a = np.asarray(stack_predict(kids, jnp.asarray(X.T), gp))
        b = reference_predict(np.asarray(kids), X, gp)
        assert np.allclose(a, b, rtol=1e-5, atol=1e-5, equal_nan=True)

    def test_point_mutation_preserves_arity(self):
        gp = GP
        pop = _rand_pop(gp, 128, seed=41)
        op = gpo.make_gp_point_mutate(gp, rate=1.0)
        rand = jax.random.uniform(jax.random.key(42), (128, op.rand_cols))
        out = np.asarray(op.batched(pop, rand))
        arity = np.asarray(gp.op_arities())
        before = np.asarray(enc.decode_ops(pop, gp))
        after = np.asarray(enc.decode_ops(jnp.asarray(out), gp))
        changed = before != after
        assert changed.any()  # rate 1.0 fires
        assert (arity[before[changed]] == arity[after[changed]]).all()

    def test_param_batched_matches_baked_rate(self):
        gp = SMALL
        pop = _rand_pop(gp, 64, seed=51)
        op = gpo.make_gp_mutate(gp, 0.4, 0.6)
        rand = jax.random.uniform(jax.random.key(52), (64, op.rand_cols))
        baked = np.asarray(op.batched(pop, rand))
        runtime = np.asarray(op.param_batched(
            pop, rand, jnp.float32(0.4), jnp.float32(0.6)
        ))
        assert np.array_equal(baked, runtime)


# ----------------------------------------------------- engine + serving


def _gp_solver(seed, gp=SMALL, pop=256, **cfg):
    X, y = _dataset(gp, n=32, seed=0)
    cfg.setdefault("use_pallas", False)
    cfg.setdefault("selection", "truncation")
    cfg.setdefault("elitism", 2)
    pga = PGA(seed=seed, config=PGAConfig(**cfg))
    pga.set_objective(symbolic_regression(X, y, gp=gp))
    pga.set_crossover(gpo.make_subtree_crossover(gp))
    pga.set_mutate(gpo.make_gp_mutate(gp, 0.4, 0.6))
    h = pga.install_population(
        enc.random_population(jax.random.key(seed), pop, gp)
    )
    return pga, h


class TestEngine:
    def test_run_improves_and_is_deterministic(self):
        pga, h = _gp_solver(7)
        pga.evaluate(h)
        before = float(jnp.max(pga.population(h).scores))
        pga.run(15)
        g1, s1 = pga.get_best_with_score(h)
        assert s1 >= before
        pga2, h2 = _gp_solver(7)
        pga2.run(15)
        g2, s2 = pga2.get_best_with_score(h2)
        assert np.array_equal(g1, g2)
        assert np.float32(s1).tobytes() == np.float32(s2).tobytes()

    def test_install_population_validates(self):
        pga = PGA(seed=0, config=PGAConfig(use_pallas=False))
        with pytest.raises(ValueError):
            pga.install_population(np.zeros(8, np.float32))
        h = pga.install_population(np.zeros((4, 8), np.float32))
        assert pga.population(h).size == 4
        assert float(pga.population(h).scores[0]) == -np.inf

    def test_gp_run_event_schema(self, tmp_path):
        from libpga_tpu.utils import telemetry

        path = str(tmp_path / "events.jsonl")
        gp = SMALL
        X, y = _dataset(gp, n=16)
        pga = PGA(seed=0, config=PGAConfig(
            use_pallas=False,
            telemetry=TelemetryConfig(history_gens=8, events_path=path),
        ))
        pga.set_objective(symbolic_regression(X, y, gp=gp))
        pga.set_crossover(gpo.make_subtree_crossover(gp))
        pga.set_mutate(gpo.make_gp_mutate(gp))
        pga.install_population(
            enc.random_population(jax.random.key(1), 64, gp)
        )
        pga.run(2)
        records = telemetry.validate_log(path)
        gp_runs = [r for r in records if r["event"] == "gp_run"]
        assert len(gp_runs) == 1
        rec = gp_runs[0]
        assert rec["max_nodes"] == gp.max_nodes
        assert rec["n_ops"] == gp.n_ops
        assert rec["n_vars"] == gp.n_vars

    def test_no_gp_run_event_for_vector_objectives(self, tmp_path):
        from libpga_tpu.utils import telemetry

        path = str(tmp_path / "events.jsonl")
        pga = PGA(seed=0, config=PGAConfig(
            use_pallas=False,
            telemetry=TelemetryConfig(history_gens=8, events_path=path),
        ))
        pga.create_population(64, 16)
        pga.set_objective("onemax")
        pga.run(2)
        kinds = {r["event"] for r in telemetry.validate_log(path)}
        assert "gp_run" not in kinds

    def test_islands_compose(self):
        gp = SMALL
        X, y = _dataset(gp, n=16)
        pga = PGA(seed=3, config=PGAConfig(use_pallas=False))
        pga.set_objective(symbolic_regression(X, y, gp=gp))
        pga.set_crossover(gpo.make_subtree_crossover(gp))
        pga.set_mutate(gpo.make_gp_mutate(gp))
        for i in range(4):
            pga.install_population(
                enc.random_population(jax.random.key(10 + i), 64, gp)
            )
        gens = pga.run_islands(8, 4, 0.1)
        assert gens == 8
        for i in range(4):
            from libpga_tpu.engine import PopulationHandle

            g = np.asarray(pga.population(PopulationHandle(i)).genomes)
            assert all(enc.is_well_formed(r, gp) for r in g)


@pytest.mark.skipif(
    len(jax.devices()) < 2, reason="needs the multi-device CPU harness"
)
class TestShards:
    def test_sharded_final_best_bit_identical(self):
        """The ISSUE 11 composition gate: a GP run at pop_shards=2
        reaches the bit-identical final best as the same-seed
        single-shard run (the round-12 panmictic-equivalence contract,
        now over tree genomes — the optimum here is EXACT recovery, so
        both runs' best scores must be bit-equal -0.0, not merely
        close)."""
        X, y = make_dataset(
            lambda a, b: a * a + b, n_samples=32, n_vars=2, seed=0
        )

        def final_best(S):
            pga = PGA(seed=11, config=PGAConfig(
                use_pallas=False, selection="truncation", elitism=2,
                pop_shards=S,
            ))
            pga.set_objective(symbolic_regression(X, y, gp=SMALL))
            pga.set_crossover(gpo.make_subtree_crossover(SMALL))
            pga.set_mutate(gpo.make_gp_mutate(SMALL, 0.4, 0.6))
            h = pga.install_population(
                enc.random_population(jax.random.key(11), 128, SMALL)
            )
            gens = pga.run(80, target=0.0)
            g, s = pga.get_best_with_score(h)
            return gens, g, np.float32(s)

        gens1, g1, s1 = final_best(1)
        assert gens1 < 80, "single-shard run never recovered the target"
        gens2, g2, s2 = final_best(2)
        assert gens2 < 80, "sharded run never recovered the target"
        assert s1.tobytes() == s2.tobytes()
        assert enc.is_well_formed(g2, SMALL)


class TestServing:
    def test_batched_gp_run_bit_identical_to_engine(self):
        from libpga_tpu.serving import BatchedRuns, RunRequest

        gp = SMALL
        X, y = _dataset(gp, n=32, seed=0)
        cfg = PGAConfig(use_pallas=False, selection="truncation",
                        elitism=2)
        # numpy snapshot FIRST: the engine donates the installed
        # buffer to its run program.
        genomes = np.asarray(
            enc.random_population(jax.random.key(99), 128, gp)
        )

        pga = PGA(seed=77, config=cfg)
        pga.set_objective(symbolic_regression(X, y, gp=gp))
        pga.set_crossover(gpo.make_subtree_crossover(gp))
        pga.set_mutate(gpo.make_gp_mutate(gp, 0.4, 0.6))
        h = pga.install_population(genomes)
        pga.run(6)

        ex = BatchedRuns(
            symbolic_regression(X, y, gp=gp),
            config=cfg,
            crossover=gpo.make_subtree_crossover(gp),
            mutate_kind=gpo.make_gp_mutate(gp, 0.4, 0.6),
        )
        res = ex.run([RunRequest(
            size=128, genome_len=gp.genome_len, n=6, seed=77,
            genomes=genomes,
            mutation_rate=0.4, mutation_sigma=0.6,
        )])[0]
        assert np.array_equal(
            np.asarray(res.genomes), np.asarray(pga.population(h).genomes)
        )

    def test_bucket_signatures_separate_encodings(self):
        from libpga_tpu.serving import BatchedRuns, RunRequest

        gp_a = SMALL
        gp_b = GPConfig(
            max_nodes=8, n_vars=2, consts=(1.0,), unary=("neg",),
            binary=("add", "sub", "mul"),
        )
        X, y = _dataset(gp_a, n=16)
        cfg = PGAConfig(use_pallas=False)

        def executor(gp):
            return BatchedRuns(
                symbolic_regression(X, y, gp=gp), config=cfg,
                crossover=gpo.make_subtree_crossover(gp),
                mutate_kind=gpo.make_gp_mutate(gp),
            )

        req = RunRequest(size=64, genome_len=16, n=2, seed=0)
        sig_a = executor(gp_a).signature(req)
        sig_b = executor(gp_b).signature(req)
        assert sig_a != sig_b
        vec = BatchedRuns("onemax", config=cfg)
        assert vec.signature(req) != sig_a


# --------------------------------------------------------------- tuning


class TestTuning:
    def test_gp_space_has_multiple_plans_on_cpu(self):
        from libpga_tpu.tuning import space as S

        ctx = S.SpaceContext(
            pop=256, genome_len=32, gp_nodes=16, gp_samples=48,
            crossover_kind="gp", mutate_kind="gp",
        )
        assert S.tuner_knobs_for(ctx) == S.GP_KNOBS
        cfgs = S.grid(ctx)
        plans = {
            (p["stack_depth"], p["opcode_block"])
            for p in (S.resolve(ctx, c) for c in cfgs)
        }
        assert len(plans) > 1, "GP knobs must resolve to >1 plan on CPU"

    def test_gp_knob_admissibility(self):
        from libpga_tpu.tuning import space as S

        gctx = S.SpaceContext(pop=256, genome_len=32, gp_nodes=16)
        vctx = S.SpaceContext(pop=256, genome_len=32)
        assert S.why_inadmissible(
            gctx, S.KernelConfig(gp_stack_depth=8)
        ) is not None  # below the bound
        assert S.why_inadmissible(
            gctx, S.KernelConfig(deme_size=256)
        ) is not None  # breed knobs inert for GP
        assert S.why_inadmissible(
            vctx, S.KernelConfig(gp_stack_depth=32)
        ) is not None  # gp knobs need a GP context
        assert S.why_inadmissible(
            gctx, S.KernelConfig(gp_stack_depth=32, gp_opcode_block=4)
        ) is None

    def test_reverse_registry_name_and_no_collision(self):
        from libpga_tpu import objectives
        from libpga_tpu.tuning import db as D

        gp = SMALL
        X, y = _dataset(gp, n=16)
        obj = symbolic_regression(X, y, gp=gp)
        name = D.objective_class(obj)
        assert name.startswith("gp_sr/")
        assert name not in objectives.names()
        # same data + encoding -> same key; different data -> different
        obj2 = symbolic_regression(X, y, gp=gp)
        assert D.objective_class(obj2) == name
        X3, y3 = _dataset(gp, n=16, seed=9)
        assert D.objective_class(
            symbolic_regression(X3, y3, gp=gp)
        ) != name
        # key round-trips through the DB string form
        key = D.current_key(64, gp.genome_len, np.float32, obj, "gp", "gp")
        assert D.TuningKey.from_dict(key.as_dict()) == key

    def test_entry_with_gp_knobs_roundtrips(self, tmp_path):
        from libpga_tpu.tuning import db as D

        key = D.TuningKey(
            pop=64, genome_len=16, dtype="float32", backend="cpu",
            device_kind="cpu", objective="gp_sr/abc", operators="gp+gp",
        )
        entry = D.TuningEntry(
            key=key,
            knobs={"gp_stack_depth": 32, "gp_opcode_block": 4},
            gens_per_sec=10.0, created=1.0,
        )
        db = D.TuningDB()
        db.add(entry)
        path = str(tmp_path / "t.json")
        db.save(path)
        loaded = D.TuningDB.load(path)
        got = loaded.lookup(key)
        assert got.knobs["gp_stack_depth"] == 32
        assert got.knobs["gp_opcode_block"] == 4

    def test_sr_resolution_precedence(self, tmp_path):
        from libpga_tpu.tuning import db as D

        gp = GPConfig(max_nodes=16, n_vars=2)
        X, y = make_dataset(
            lambda a, b: a + b, n_samples=16, n_vars=2
        )
        obj = symbolic_regression(X, y, gp=gp)
        key = D.current_key(64, gp.genome_len, np.float32, obj, "gp", "gp")
        db = D.TuningDB()
        db.add(D.TuningEntry(
            key=key,
            knobs={
                "gp_stack_depth": 32, "gp_opcode_block": 4,
                "gp_dispatch": "blocked",
            },
            gens_per_sec=1.0, created=1.0,
        ))
        path = str(tmp_path / "t.json")
        db.save(path)
        pop = _rand_pop(gp, 64)
        try:
            D.set_tuning_db(path)
            obj.rows(pop)
            (knobs,) = [
                v for k, v in obj.resolved.items() if k[0] == 64
            ]
            assert knobs[:3] == (32, 4, "blocked")
            assert knobs[3] == {
                "gp_stack_depth": "db", "gp_opcode_block": "db",
                "gp_dispatch": "db",
            }
            user = symbolic_regression(X, y, gp=gp, stack_depth=64)
            user.rows(pop)
            (uk,) = [
                v for k, v in user.resolved.items() if k[0] == 64
            ]
            # user beats db, db fills the rest
            assert uk[:3] == (64, 4, "blocked")
        finally:
            D.set_tuning_db(None)

    def test_resolve_config_knobs_reads_gp_fields_as_none(self):
        from libpga_tpu.tuning import db as D

        knobs, prov = D.resolve_config_knobs(PGAConfig(), None)
        assert knobs["gp_stack_depth"] is None
        assert knobs["gp_opcode_block"] is None
        assert prov is None


# ---------------------------------------------------------- C ABI bridge


class TestCapiBridge:
    def test_gp_config_sr_objective_and_error_surfaces(self):
        from libpga_tpu import capi_bridge as b

        h = b.init(123)
        try:
            X = np.random.default_rng(0).uniform(
                -1, 1, (16, 2)
            ).astype(np.float32)
            y = (X[:, 0] ** 2 + X[:, 1]).astype(np.float32)
            # Error surfaces BEFORE any state: SR needs gp_config,
            # degenerate encodings are rejected.
            with pytest.raises(ValueError):
                b.set_objective_sr(h, X.tobytes(), y.tobytes(), 16)
            with pytest.raises(ValueError):
                b.gp_config(h, 1, 2, -1.0)
            with pytest.raises(ValueError):
                b.gp_create_population(h, 64)
            assert b.gp_n_vars(h) == -1
            # The real config installs; errors above left nothing.
            b.gp_config(h, 8, 2, -1.0)
            assert b.gp_n_vars(h) == 2
            idx = b.gp_create_population(h, 64)
            b.set_objective_sr(h, X.tobytes(), y.tobytes(), 16)
            # Bad sample count rejected, installed objective intact
            # (proven by running).
            with pytest.raises(ValueError):
                b.set_objective_sr(h, X.tobytes(), y.tobytes(), 0)
            assert b.run(h, 3, 0, 0.0) == 3
            arr = np.frombuffer(b.get_best(h, idx), np.float32)
            assert arr.shape == (16,)
            assert ((arr >= 0) & (arr < 1)).all()
        finally:
            b.deinit(h)


# --------------------------------------------------- structural guards


class TestByteIdentity:
    def test_vector_engine_stablehlo_unchanged_by_gp(self):
        """ISSUE 11 bugfix guard: a vector-genome engine's traced run
        program is BYTE-IDENTICAL with the GP subsystem imported and
        exercised (the subsystem must be purely additive — no global
        state, no monkey-patching). Gate: ``analysis.fingerprint``."""
        from libpga_tpu.analysis import fingerprint

        def lowered_text():
            pga = PGA(seed=0, config=PGAConfig(use_pallas=False))
            pga.create_population(128, 16)
            pga.set_objective("onemax")
            fn = pga._compiled_run(128, 16)
            args = (
                pga.population(pga._handles()[0]).genomes,
                jax.random.key(1), jnp.int32(3), jnp.float32(jnp.inf),
                pga._mutate_params(),
            )
            return fingerprint(fn, *args)

        before = lowered_text()
        # Exercise the subsystem end to end, then re-lower.
        gp = SMALL
        X, y = _dataset(gp, n=8)
        obj = symbolic_regression(X, y, gp=gp)
        obj.rows(_rand_pop(gp, 16))
        op = gpo.make_gp_mutate(gp)
        op.batched(
            _rand_pop(gp, 8),
            jax.random.uniform(jax.random.key(0), (8, op.rand_cols)),
        )
        after = lowered_text()
        assert before == after
