"""GP eval-time program optimizer (ISSUE 19): fold + DCE + compact.

Covers the acceptance gates:

- optimized evaluation is BIT-EQUAL to unoptimized evaluation on
  random well-formed programs AND arbitrary gene noise (the fold uses
  the evaluator's own jnp table, so device rounding is identical);
- on IEEE-exact op sets (neg/add/sub/mul/div — correctly rounded on
  both numpy and XLA CPU) fitness is bit-equal to the numpy oracle
  piped through the interpreter's own RMSE expression;
- constant-only programs fold to a single ``LIT`` token; max-depth
  chains survive; live lengths match ``program_structure`` exactly
  when nothing folds and never exceed it anywhere;
- the live-length trip bound is a RUNTIME scalar: populations with
  different length distributions share one compiled program;
- the ``gp_dispatch`` tuning knob: domain registration, genome codec
  round-trip, admissibility (GP-context-only, ValueError on junk),
  distinct tuner plan keys, tuning-DB entry round-trip;
- serving buckets split on the new encoding axes (``optimize``,
  ``dispatch`` ride ``GPConfig.cache_key``).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from libpga_tpu import GPConfig, PGAConfig
from libpga_tpu.gp import encoding as enc
from libpga_tpu.gp import operators as gpo
from libpga_tpu.gp.interpreter import (
    make_eval_rows,
    stack_predict,
    stack_predict_program,
)
from libpga_tpu.gp.optimize import (
    EvalProgram,
    compaction_stats,
    lit_op,
    live_lengths,
    optimize_for_eval,
)
from libpga_tpu.gp.reference import reference_predict
from libpga_tpu.gp.sr import make_dataset, symbolic_regression
from libpga_tpu.ops.evaluate import evaluate

#: Op sets where every operation is correctly rounded by BOTH numpy
#: and XLA CPU (IEEE +,-,*,/ and negation) — the configs where
#: fitness-vs-oracle equality is exact, not approximate. Transcendental
#: sets (sin/cos/exp) differ from numpy by ulps (pre-existing, both
#: evaluator paths equally) and are covered by the opt-vs-unopt
#: bitwise gates instead.
ARITH = [
    GPConfig(max_nodes=10, n_vars=2, unary=("neg",),
             binary=("add", "sub", "mul", "div")),
    GPConfig(max_nodes=8, n_vars=1, consts=(0.5, -2.0, 3.0),
             unary=("neg",), binary=("add", "mul")),
    GPConfig(max_nodes=16, n_vars=3, consts=(), unary=(),
             binary=("add", "sub", "mul")),
]
FULL = GPConfig()  # default transcendental-bearing table


def _pop(gp, n, seed=0):
    return enc.random_population(jax.random.key(seed), n, gp)


def _noise(gp, n, seed=0, lo=-1.5, hi=2.5):
    rng = np.random.default_rng(seed)
    return jnp.asarray(
        rng.uniform(lo, hi, size=(n, gp.genome_len)).astype(np.float32)
    )


def _data(gp, n=24, seed=0):
    return make_dataset(
        lambda *xs: xs[0] * xs[-1] + xs[0],
        n_samples=n, n_vars=gp.n_vars, seed=seed,
    )


def _bits(a):
    return np.asarray(a).view(np.int32)


def _oracle_scores(preds, ya):
    """The numpy oracle's predictions pushed through the SAME jnp RMSE
    expression the interpreter uses — reduction order and sanitization
    identical, so score comparison is bit-level."""
    err = jnp.asarray(preds) - jnp.asarray(ya)[None, :]
    s = -jnp.sqrt(jnp.mean(err * err, axis=1))
    return np.asarray(
        jnp.where(jnp.isfinite(s), s, -jnp.float32(jnp.inf))
    )


# ----------------------------------------------------- oracle equality


class TestOracleEquality:
    @pytest.mark.parametrize("gp", ARITH)
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_fitness_bit_equal_oracle_well_formed(self, gp, seed):
        X, y = _data(gp)
        m = _pop(gp, 128, seed)
        rows = make_eval_rows(gp, X, y, optimize=True)
        got = np.asarray(rows(m))
        want = _oracle_scores(
            reference_predict(np.asarray(m), X, gp), y
        )
        assert np.array_equal(_bits(got), _bits(want))

    @pytest.mark.parametrize("gp", ARITH)
    @pytest.mark.parametrize("seed", [3, 4])
    def test_fitness_bit_equal_oracle_arbitrary_noise(self, gp, seed):
        X, y = _data(gp)
        m = _noise(gp, 128, seed)
        got = np.asarray(make_eval_rows(gp, X, y, optimize=True)(m))
        want = _oracle_scores(
            reference_predict(np.asarray(m), X, gp), y
        )
        assert np.array_equal(_bits(got), _bits(want))

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_opt_vs_unopt_bit_equal_full_ops(self, seed):
        X, y = _data(FULL)
        m = _pop(FULL, 192, seed)
        on = np.asarray(make_eval_rows(FULL, X, y, optimize=True)(m))
        off = np.asarray(make_eval_rows(FULL, X, y, optimize=False)(m))
        assert np.array_equal(_bits(on), _bits(off))

    @pytest.mark.parametrize("seed", [5, 6])
    def test_opt_vs_unopt_bit_equal_noise_full_ops(self, seed):
        X, y = _data(FULL)
        m = _noise(FULL, 192, seed)
        on = np.asarray(make_eval_rows(FULL, X, y, optimize=True)(m))
        off = np.asarray(make_eval_rows(FULL, X, y, optimize=False)(m))
        assert np.array_equal(_bits(on), _bits(off))

    def test_predictions_close_to_oracle_full_ops(self):
        X, _ = _data(FULL)
        m = _pop(FULL, 128, 7)
        xt = np.ascontiguousarray(np.asarray(X, np.float32).T)
        got = np.asarray(
            stack_predict_program(optimize_for_eval(m, FULL), xt, FULL)
        )
        want = reference_predict(np.asarray(m), X, FULL)
        assert np.allclose(got, want, rtol=1e-5, atol=1e-5,
                           equal_nan=True)

    def test_constant_only_folds_to_single_lit(self):
        gp = ARITH[1]  # consts (0.5, -2.0, 3.0)
        g = enc.encode_program(
            [("const", 0), ("const", 1), "add", ("const", 2), "mul"],
            gp,
        )
        prog = optimize_for_eval(g[None, :], gp)
        assert int(prog.length[0]) == 1
        assert int(prog.ops[0, 0]) == lit_op(gp)
        assert float(prog.args[0, 0]) == np.float32(
            (np.float32(0.5) + np.float32(-2.0)) * np.float32(3.0)
        )

    def test_max_depth_chain_survives(self):
        gp = ARITH[0]
        toks = [("var", 0)]
        while len(toks) + 2 <= gp.max_nodes:
            toks += [("var", 1), "add"]
        g = enc.encode_program(toks, gp)
        X, y = _data(gp)
        on = np.asarray(
            make_eval_rows(gp, X, y, optimize=True)(g[None, :])
        )
        want = _oracle_scores(
            reference_predict(np.asarray(g)[None, :], X, gp), y
        )
        assert np.array_equal(_bits(on), _bits(want))
        # nothing folds (no consts involved): length is preserved
        assert int(live_lengths(g[None, :], gp)[0]) == len(toks)

    def test_dce_removes_buried_subtree(self):
        # A non-strictly-well-formed gene stream that buries a value:
        # [x0, x1, x0, add] leaves sp=2 — x0's push is never consumed
        # and is not the final top, so DCE deletes it.
        gp = ARITH[0]
        g = enc.encode_program(
            [("var", 0), ("var", 1), ("var", 0), "add"], gp,
        )
        prog = optimize_for_eval(g[None, :], gp)
        assert int(prog.length[0]) == 3  # x1 x0 add
        X, y = _data(gp)
        on = np.asarray(
            make_eval_rows(gp, X, y, optimize=True)(g[None, :])
        )
        off = np.asarray(
            make_eval_rows(gp, X, y, optimize=False)(g[None, :])
        )
        assert np.array_equal(_bits(on), _bits(off))


# -------------------------------------------------------- live lengths


class TestLiveLengths:
    def test_matches_structure_when_nothing_folds(self):
        # No consts -> no fold roots; random well-formed programs are
        # strictly well-formed (final sp == 1) -> no dead code either:
        # post-optimization length IS the skip-rule live count.
        gp = ARITH[2]
        m = _pop(gp, 256, 1)
        got = np.asarray(live_lengths(m, gp))
        want = np.asarray(enc.program_structure(m, gp).length)
        assert np.array_equal(got, want)

    def test_never_exceeds_structure_anywhere(self):
        for gp in ARITH + [FULL]:
            m = _noise(gp, 128, 9)
            after = np.asarray(live_lengths(m, gp))
            before = np.asarray(enc.program_structure(m, gp).length)
            assert np.all(after <= before)
            assert np.all(after >= 0)

    def test_compaction_stats_schema(self):
        m = _pop(FULL, 64, 2)
        st = compaction_stats(m, FULL)
        assert st["pop"] == 64
        assert st["max_nodes"] == FULL.max_nodes
        assert st["mean_live_after"] <= st["mean_live_before"]
        assert 0.0 <= st["removed_frac"] <= 1.0
        assert st["max_live_after"] <= FULL.max_nodes

    def test_eval_program_tail_is_padded(self):
        m = _pop(FULL, 32, 3)
        prog = optimize_for_eval(m, FULL)
        ops = np.asarray(prog.ops)
        ln = np.asarray(prog.length)
        for i in range(ops.shape[0]):
            assert np.all(ops[i, ln[i]:] == enc.PAD_OP)


# -------------------------------------------- no recompiles across gens


class TestNoRecompile:
    def test_trip_bound_is_runtime_scalar(self):
        gp = FULL
        X, _ = _data(gp)
        xt = np.ascontiguousarray(np.asarray(X, np.float32).T)

        @jax.jit
        def f(m):
            return stack_predict_program(
                optimize_for_eval(m, gp), xt, gp
            )

        f(_pop(gp, 128, 0)).block_until_ready()
        # Different generation, different live-length distribution —
        # short constant-only rows force a different block max.
        short = _noise(gp, 128, 11, lo=0.0, hi=0.2)
        f(short).block_until_ready()
        assert f._cache_size() == 1

    def test_evaluate_hook_shares_one_compile(self):
        gp = FULL
        X, y = _data(gp)
        obj = symbolic_regression(X, y, gp=gp)
        assert hasattr(obj, "prepare_eval")

        @jax.jit
        def f(m):
            return evaluate(obj, m)

        f(_pop(gp, 128, 0)).block_until_ready()
        f(_noise(gp, 128, 12)).block_until_ready()
        assert f._cache_size() == 1

    def test_parsimony_or_optimize_off_skip_hook(self):
        gp = FULL
        X, y = _data(gp)
        assert not hasattr(
            symbolic_regression(X, y, gp=gp, parsimony=0.01),
            "prepare_eval",
        )
        assert not hasattr(
            symbolic_regression(X, y, gp=GPConfig(optimize=False)),
            "prepare_eval",
        )


# ------------------------------------------------------ dispatch knob


class TestDispatchKnob:
    def test_domain_and_knob_registration(self):
        from libpga_tpu.tuning import space as S

        assert S.DOMAINS["gp_dispatch"] == (None, "dense", "blocked")
        assert "gp_dispatch" in S.GP_KNOBS
        assert S.DOMAINS["gp_dispatch"][0] is None  # AUTO first

    def test_codec_round_trip(self):
        from libpga_tpu.tuning import space as S

        for i, val in enumerate(S.DOMAINS["gp_dispatch"]):
            cfg = S.config_from_indices((0, 0, i), S.GP_KNOBS)
            assert cfg.gp_dispatch == val
            back = S.indices_from_config(cfg, S.GP_KNOBS)
            assert tuple(back)[2] == i
        # the float-gene decode is total over the new axis too
        assert S.config_from_genes(
            (0.0, 0.0, 0.99), S.GP_KNOBS
        ).gp_dispatch == "blocked"

    def test_admissibility(self):
        from libpga_tpu.tuning import space as S

        gp_ctx = S.SpaceContext(
            pop=256, genome_len=32, gp_nodes=16, gp_samples=48,
            crossover_kind="gp", mutate_kind="gp",
        )
        vec_ctx = S.SpaceContext(pop=256, genome_len=32)
        ok = S.KernelConfig(gp_dispatch="blocked")
        assert S.admissible(gp_ctx, ok)
        why = S.why_inadmissible(vec_ctx, ok)
        assert why is not None and "gp_dispatch" in why

    def test_explicit_junk_dispatch_raises(self):
        from libpga_tpu.ops.gp_eval import gp_eval_plan

        with pytest.raises(ValueError, match="gp_dispatch"):
            gp_eval_plan(64, FULL, 24, dispatch="simd")
        with pytest.raises(ValueError):
            GPConfig(dispatch="simd")

    def test_plan_keys_distinguish_dispatch(self):
        from libpga_tpu.tuning import space as S
        from libpga_tpu.tuning import tuner as T

        ctx = S.SpaceContext(
            pop=256, genome_len=32, gp_nodes=16, gp_samples=48,
            crossover_kind="gp", mutate_kind="gp",
        )
        dense = T._plan_key(ctx, S.KernelConfig(gp_dispatch="dense"),
                            False)
        blocked = T._plan_key(
            ctx, S.KernelConfig(gp_dispatch="blocked"), False
        )
        assert dense != blocked
        assert T._canonical_knobs(blocked)["gp_dispatch"] == "blocked"

    def test_db_entry_round_trips_dispatch(self, tmp_path):
        from libpga_tpu.tuning import db as D

        key = D.TuningKey(
            pop=64, genome_len=32, dtype="float32", backend="cpu",
            device_kind="cpu", objective="gp_sr/xyz", operators="gp+gp",
        )
        db = D.TuningDB()
        db.add(D.TuningEntry(
            key=key,
            knobs={"gp_stack_depth": 16, "gp_opcode_block": 2,
                   "gp_dispatch": "blocked"},
            gens_per_sec=5.0, created=1.0,
        ))
        path = str(tmp_path / "t.json")
        db.save(path)
        got = D.TuningDB.load(path).lookup(key)
        assert got.knobs["gp_dispatch"] == "blocked"

    @pytest.mark.parametrize("seed", [0, 1])
    def test_blocked_bit_equal_dense(self, seed):
        X, _ = _data(FULL)
        xt = np.ascontiguousarray(np.asarray(X, np.float32).T)
        m = _pop(FULL, 128, seed)
        dense = np.asarray(
            stack_predict(m, xt, FULL, dispatch="dense")
        )
        blocked = np.asarray(
            stack_predict(m, xt, FULL, dispatch="blocked")
        )
        assert np.array_equal(_bits(dense), _bits(blocked))

    def test_with_knobs_carries_dispatch(self):
        gp = GPConfig(max_nodes=16, n_vars=2)
        X, y = _data(gp)
        obj = symbolic_regression(X, y, gp=gp)
        o2 = obj.with_knobs(dispatch="blocked")
        assert o2.knob_args == (None, None, "blocked")
        m = _pop(gp, 64, 0)
        assert np.array_equal(
            _bits(evaluate(o2, m)), _bits(evaluate(obj, m))
        )


# --------------------------------------------------- serving signatures


class TestServingSignatures:
    def test_buckets_split_on_optimize_and_dispatch(self):
        from libpga_tpu.serving import BatchedRuns, RunRequest

        X, y = _data(GPConfig(max_nodes=8, n_vars=2))
        cfg = PGAConfig(use_pallas=False)

        def executor(gp):
            return BatchedRuns(
                symbolic_regression(X, y, gp=gp), config=cfg,
                crossover=gpo.make_subtree_crossover(gp),
                mutate_kind=gpo.make_gp_mutate(gp),
            )

        req = RunRequest(size=64, genome_len=16, n=2, seed=0)
        base = GPConfig(max_nodes=8, n_vars=2)
        sig = executor(base).signature(req)
        sig_off = executor(
            GPConfig(max_nodes=8, n_vars=2, optimize=False)
        ).signature(req)
        sig_blk = executor(
            GPConfig(max_nodes=8, n_vars=2, dispatch="blocked")
        ).signature(req)
        assert sig != sig_off
        assert sig != sig_blk
        assert sig_off != sig_blk


# ------------------------------------------------- fused-kernel parity


class TestFusedParity:
    def test_fused_optimize_paths_bit_equal(self):
        from libpga_tpu.ops.gp_eval import make_gp_eval
        from jax.experimental.pallas import tpu as pltpu

        gp = FULL
        X, y = _data(gp, n=32)
        m = _pop(gp, 64, 0)
        with pltpu.force_tpu_interpret_mode():
            off = make_gp_eval(
                GPConfig(optimize=False), X, y, pop=64
            )(m)
            on = make_gp_eval(gp, X, y, pop=64)(m)
            prog_in = make_gp_eval(gp, X, y, pop=64)(
                optimize_for_eval(m, gp)
            )
            blk = make_gp_eval(gp, X, y, pop=64, dispatch="blocked")(m)
        assert np.array_equal(_bits(on), _bits(off))
        assert np.array_equal(_bits(prog_in), _bits(on))
        assert np.array_equal(_bits(blk), _bits(on))

    def test_plan_carries_dispatch_and_optimize(self):
        from libpga_tpu.ops.gp_eval import gp_eval_plan

        plan = gp_eval_plan(256, FULL, 64)
        assert plan["dispatch"] == "dense"
        assert plan["optimize"] is True
        plan2 = gp_eval_plan(
            256, GPConfig(optimize=False), 64, dispatch="blocked"
        )
        assert plan2["dispatch"] == "blocked"
        assert plan2["optimize"] is False

    def test_plan_cost_prices_live_length(self):
        from libpga_tpu.ops.gp_eval import gp_eval_plan, gp_plan_cost

        plan = gp_eval_plan(256, FULL, 64)
        full = gp_plan_cost(plan, 256, FULL, 64)
        live = gp_plan_cost(plan, 256, FULL, 64, live_length=6.0)
        assert live["flops_per_eval"] < full["flops_per_eval"]
        assert live["tokens_per_program"] == 6.0
        # legacy configs ignore live_length (they run the full cap)
        plan_off = gp_eval_plan(256, GPConfig(optimize=False), 64)
        off = gp_plan_cost(
            plan_off, 256, GPConfig(optimize=False), 64,
            live_length=6.0,
        )
        assert off["tokens_per_program"] == float(FULL.max_nodes)
