"""NEGATIVE fixture: the sanctioned durable-write shapes.

Never imported — linted by tests/test_analysis.py only.
"""

import json
import os

import numpy as np


def publish_result(spool_dir, tid, payload):
    # temp name + os.replace: the atomic-rename discipline.
    meta_path = os.path.join(spool_dir, "results", f"{tid}.json")
    tmp = f"{meta_path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(payload, fh)
    os.replace(tmp, meta_path)


def save_checkpoint(spool_dir, tid, genomes):
    final = os.path.join(spool_dir, "ckpt", f"{tid}.npz")
    tmp = f"{final}.{os.getpid()}.tmp.npz"
    np.savez(tmp, g=genomes)
    os.replace(tmp, final)


def append_trace(spool_dir, tid, line):
    # append mode: the O_APPEND whole-line protocol is sanctioned.
    with open(os.path.join(spool_dir, "traces", f"{tid}.jsonl"), "a") as fh:
        fh.write(line + "\n")


def read_result(spool_dir, tid):
    # reads are never the rule's business.
    with open(os.path.join(spool_dir, "results", f"{tid}.json")) as fh:
        return json.load(fh)
