"""Fixture: a suppression that silences nothing — itself a finding
(stale exemptions must not accumulate).

Never imported — linted by tests/test_analysis.py only.
"""


def harmless():
    x = 1  # pga-lint: disable=spool-atomic-write
    return x
