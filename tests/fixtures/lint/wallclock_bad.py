"""POSITIVE fixture: host-environment reads inside traced code.

Never imported — linted by tests/test_analysis.py only.
"""

import time

import numpy as np

import jax
import jax.numpy as jnp


def run(genomes, n):
    def cond(carry):
        g, gen = carry
        return gen < n

    def body(carry):
        g, gen = carry
        # BAD: baked in at trace time, silently stale afterwards
        noise = time.time()
        return g + noise, gen + 1

    return jax.lax.while_loop(cond, body, (genomes, jnp.int32(0)))


def scored(genomes):
    def scorer(g):
        # BAD: host RNG breaks bit-identical replay
        return jnp.sum(g) * np.random.rand()

    return jax.jit(scorer)(genomes)


def transitive(genomes):
    def helper(g):
        return g * time.monotonic()  # BAD: reached through the walk

    def step(g):
        return helper(g) + 1.0

    return jax.jit(step)(genomes)
