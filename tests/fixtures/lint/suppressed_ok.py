"""Fixture: a real violation silenced by a scoped, documented
suppression — the sanctioned escape hatch.

Never imported — linted by tests/test_analysis.py only.
"""

import json
import os


def torn_file_simulation(spool_dir):
    # Deliberate torn write: this exercises a reader's defense path,
    # exactly the legitimate-suppression shape.
    path = os.path.join(spool_dir, "results", "torn.json")
    with open(path, "w") as fh:  # pga-lint: disable=spool-atomic-write
        fh.write(json.dumps({"x": 1})[:7])
