"""POSITIVE fixture: bare writes landing in durable spool state.

Never imported — linted by tests/test_analysis.py only.
"""

import json
import os

import numpy as np


def publish_result(spool_dir, tid, payload):
    # BAD: a crash mid-write leaves a torn result a reader will parse.
    meta_path = os.path.join(spool_dir, "results", f"{tid}.json")
    with open(meta_path, "w", encoding="utf-8") as fh:  # line 15: flagged
        json.dump(payload, fh)


def save_checkpoint(spool_dir, tid, genomes):
    # BAD: np.savez straight onto the durable checkpoint name.
    np.savez(os.path.join(spool_dir, "ckpt", f"{tid}.npz"), g=genomes)
