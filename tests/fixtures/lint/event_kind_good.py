"""NEGATIVE fixture: schema-honest event emissions.

Never imported — linted by tests/test_analysis.py only.
"""


class Emitter:
    def _emit(self, event, **fields):
        pass


def report(e: Emitter, extra):
    # registered kind, every required field present
    e._emit("run_start", population_size=256, genome_len=16, n=1)
    # registered kind with **kwargs: membership check only
    e._emit("ticket_done", bucket="b", **extra)
    # dynamic kind: not a literal, out of static scope
    kind = "run_end" if extra else "run_start"
    e._emit(kind, generations=3, seconds=0.1, best=1.0,
            population_size=1, genome_len=1, n=1)
