"""NEGATIVE fixture: every protected mutation holds the lock.

Never imported — linted by tests/test_analysis.py only.
"""

import threading


class Registry:
    def __init__(self):
        self._series = {}
        self._listeners = []  # never locked: unprotected by choice
        self._lock = threading.Lock()

    def record(self, name, value):
        with self._lock:
            self._series[name] = value

    def reset(self):
        with self._lock:
            self._series.clear()

    def add_listener(self, fn):
        # _listeners is never mutated under the lock anywhere, so the
        # self-calibrating rule leaves it alone.
        self._listeners.append(fn)

    def snapshot(self):
        with self._lock:
            return dict(self._series)


class NoLock:
    """A lockless class: the rule does not apply at all."""

    def __init__(self):
        self.items = []

    def push(self, x):
        self.items.append(x)
