"""NEGATIVE fixture: host reads stay OUTSIDE the traced closure.

Never imported — linted by tests/test_analysis.py only.
"""

import time

import jax
import jax.numpy as jnp


def run(genomes, n):
    started = time.time()  # host side: fine

    def cond(carry):
        g, gen = carry
        return gen < n

    def body(carry):
        g, gen = carry
        key = jax.random.key(gen)  # jax RNG is traced-pure: fine
        return g + jax.random.uniform(key, g.shape), gen + 1

    out = jax.lax.while_loop(cond, body, (genomes, jnp.int32(0)))
    elapsed = time.time() - started
    return out, elapsed


def cond(pred):
    """A local helper named like a trace entry: its args must NOT be
    pulled into the traced set (it is not jax.lax.cond)."""
    return pred


def uses_local_cond(flag):
    def reads_clock():
        return time.time()

    return cond(reads_clock)
