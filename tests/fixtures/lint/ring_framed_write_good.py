"""NEGATIVE fixture: the sanctioned shared-mapping write shapes.

Never imported — linted by tests/test_analysis.py only.
"""

import mmap
import struct
import zlib


def _framed_store(mm, off, payload):
    # THE sanctioned shape: seqlock framing inside a _framed_* writer.
    (seq,) = struct.unpack_from("<I", mm, off)
    struct.pack_into("<I", mm, off, (seq + 1) & 0xFFFFFFFF)
    mm[off + 4:off + 4 + len(payload)] = payload
    struct.pack_into(
        "<I", mm, off + 4 + len(payload), zlib.crc32(payload) & 0xFFFFFFFF
    )
    struct.pack_into("<I", mm, off, (seq + 2) & 0xFFFFFFFF)


def read_head(fd):
    # reads are never the rule's business (readers validate seq + CRC).
    mm = mmap.mmap(fd, 4096, prot=mmap.PROT_READ)
    return struct.unpack_from("<Q", mm, 256)[0]


def build_image(size, pid):
    # Staging a bytearray image for an atomic file replace is not a
    # shared-mapping write — nobody can observe it mid-build.
    buf = bytearray(size)
    struct.pack_into("<Q", buf, 0, pid)
    buf[8:16] = b"PGARING1"
    return bytes(buf)


def store_slot(mm, off, payload):
    # delegating to the framed writer is the non-_framed caller shape.
    _framed_store(mm, off, payload)
