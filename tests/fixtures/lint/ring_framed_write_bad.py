"""POSITIVE fixture: raw shared-mapping mutations outside a framed
writer — every site here must trip ``ring-framed-write``.

Never imported — linted by tests/test_analysis.py only.
"""

import mmap
import struct


def bump_head(fd, head):
    # Slice-assign straight onto the mapping: a reader racing this
    # write sees torn bytes with no seq/CRC to reject them by.
    mm = mmap.mmap(fd, 4096)
    mm[256:264] = struct.pack("<Q", head)


def stamp_heartbeat(ring, now):
    # pack_into on the ring's mapping attribute — same torn window.
    struct.pack_into("<d", ring._mm, 4096, now)


class SlotWriter:
    def __init__(self, mm):
        self._mm = mm

    def write_slot(self, idx, payload):
        # method body is not a _framed_* writer: still a violation.
        self._mm[4096 + idx * 128:4096 + idx * 128 + len(payload)] = payload
