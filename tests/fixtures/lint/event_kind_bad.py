"""POSITIVE fixture: unregistered / underfilled event emissions.

Never imported — linted by tests/test_analysis.py only.
"""


class Emitter:
    def _emit(self, event, **fields):
        pass


def report(e: Emitter):
    # BAD: kind not in telemetry.EVENT_FIELDS (a round-17-style typo).
    e._emit("pbt_epohc", epoch=1, exploited=2, best=3.0)
    # BAD: registered kind missing a required field (no **kwargs escape).
    e._emit("run_start", population_size=256, genome_len=16)
