"""POSITIVE fixture: lock-protected state mutated without the lock.

Never imported — linted by tests/test_analysis.py only.
"""

import threading


class Registry:
    def __init__(self):
        self._series = {}
        self._lock = threading.Lock()

    def record(self, name, value):
        with self._lock:
            self._series[name] = value  # calibrates: _series is protected

    def reset(self):
        self._series.clear()  # BAD: unlocked mutation of protected state

    def bulk(self, items):
        self._series.update(items)  # BAD: same
