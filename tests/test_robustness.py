"""Fault-tolerant execution layer (robustness/): ISSUE 5 acceptance.

The contracts under test:

- **deterministic injection**: a :class:`FaultPlan` fires on exactly the
  configured call (``at_call_n``), probability plans replay identically
  under the same seed, ``times`` bounds fires, and the registry records
  every injection (+ emits schema-valid ``fault_injected`` events);
- **disabled-path purity**: with no plan installed and under every
  ``fallback`` setting the engine's compiled run loop lowers to
  byte-identical StableHLO (the host-side robustness machinery can
  never perturb a traced program) — the same structural pattern as the
  telemetry zero-cost-off gate;
- **graceful degradation**: a kernel-build failure under
  ``fallback="xla"`` degrades the config to the XLA path (bit-identical
  to a plain XLA run, one warning, a ``degraded`` event);
  ``fallback="raise"`` propagates;
- **supervision**: retry-with-rollback replays the engine key chain
  (a supervised run that failed and retried — or died and resumed — is
  bit-identical to an uninterrupted same-seed run with the same
  cadence), backoff grows exponentially with deterministic jitter,
  NaN storms roll back, the stall watchdog aborts, and the C-ABI
  bridge surface (``set_fault_plan``/``supervised_run``) round-trips.
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from libpga_tpu import PGA, PGAConfig, TelemetryConfig
from libpga_tpu.robustness import faults
from libpga_tpu.robustness.faults import FaultPlan, InjectedFault
from libpga_tpu.robustness.supervisor import (
    NaNStorm,
    RetryPolicy,
    SupervisedReport,
    read_meta,
    supervised_run,
)

POP, LEN = 64, 8


def _engine(seed=5, tel=None, **cfg):
    pga = PGA(seed=seed, config=PGAConfig(use_pallas=False, telemetry=tel,
                                          **cfg))
    pga.create_population(POP, LEN)
    pga.set_objective("onemax")
    return pga


def _genomes(pga):
    # explicit host copy: comparisons must never read a zero-copy view
    # of a device buffer a later donated dispatch could reuse
    return np.array(pga._populations[0].genomes, copy=True)


NOSLEEP = staticmethod(lambda s: None)


# ------------------------------------------------------------ fault registry


def test_plan_validation():
    with pytest.raises(ValueError, match="site"):
        FaultPlan("")
    with pytest.raises(ValueError, match="kind"):
        FaultPlan("objective.eval", kind="explode", at_call_n=1)
    with pytest.raises(ValueError, match="trigger"):
        FaultPlan("objective.eval")
    with pytest.raises(ValueError, match="1-based"):
        FaultPlan("objective.eval", at_call_n=0)
    with pytest.raises(ValueError, match="probability"):
        FaultPlan("objective.eval", probability=1.5)
    with pytest.raises(ValueError, match="times"):
        FaultPlan("objective.eval", at_call_n=1, times=0)


def test_at_call_n_fires_exactly_once():
    reg = faults.FaultRegistry((FaultPlan("s", at_call_n=3),))
    assert reg.fire("s") is False
    assert reg.fire("other") is False  # other sites don't advance "s"
    assert reg.fire("s") is False
    with pytest.raises(InjectedFault) as ei:
        reg.fire("s")
    assert ei.value.site == "s" and ei.value.call == 3
    assert reg.fire("s") is False  # times=1 default: exhausted
    assert reg.calls == {"s": 4, "other": 1}
    assert reg.injected == [{"site": "s", "kind": "raise", "call": 3}]


def test_probability_plans_replay_deterministically():
    def pattern(seed):
        reg = faults.FaultRegistry(
            (FaultPlan("s", probability=0.4, times=None),), seed=seed
        )
        fired = []
        for i in range(50):
            try:
                reg.fire("s")
                fired.append(False)
            except InjectedFault:
                fired.append(True)
        return fired

    assert pattern(7) == pattern(7)
    assert any(pattern(7)) and not all(pattern(7))
    assert pattern(7) != pattern(8)


def test_times_bounds_unlimited_and_nan_kind():
    reg = faults.FaultRegistry(
        (FaultPlan("s", kind="nan", probability=1.0, times=2),)
    )
    assert reg.fire("s") is True
    assert reg.fire("s") is True
    assert reg.fire("s") is False  # exhausted
    reg2 = faults.FaultRegistry(
        (FaultPlan("s", kind="nan", probability=1.0, times=None),)
    )
    assert all(reg2.fire("s") for _ in range(10))


def test_active_context_restores_previous_plan():
    assert faults.PLAN is None
    outer = faults.install(FaultPlan("a", at_call_n=1))
    try:
        with faults.active(FaultPlan("b", at_call_n=1)) as inner:
            assert faults.PLAN is inner
        assert faults.PLAN is outer
    finally:
        faults.clear()
    assert faults.PLAN is None


def test_fault_injected_events_validate(tmp_path):
    from libpga_tpu.utils import telemetry

    path = str(tmp_path / "faults.jsonl")
    with telemetry.EventLog(path) as log:
        with faults.active(
            FaultPlan("objective.eval", at_call_n=1), events=log
        ):
            pga = _engine()
            with pytest.raises(InjectedFault):
                pga.run(3)
    records = telemetry.validate_log(path)
    kinds = [r["event"] for r in records]
    assert "fault_injected" in kinds
    rec = next(r for r in records if r["event"] == "fault_injected")
    assert rec["site"] == "objective.eval" and rec["kind"] == "raise"


# ------------------------------------------------------ disabled-path purity


def test_disabled_path_lowering_is_byte_identical():
    """No fault plan + any fallback setting: the compiled run loop
    fingerprints identically across configurations (and to the
    telemetry purity gate's replica, transitively) — the robustness
    layer is host-side only. The digest is ``analysis.fingerprint``,
    the same canonical-StableHLO gate every other purity test uses."""
    import jax

    from libpga_tpu.analysis import fingerprint

    prints = []
    for fallback in ("xla", "raise"):
        pga = _engine(fallback=fallback)
        pop = pga._populations[0]
        args = (
            pop.genomes, jax.random.key(0), jnp.int32(3),
            jnp.float32(jnp.inf), pga._mutate_params(),
        )
        prints.append(
            fingerprint(pga._compiled_run(pop.size, pop.genome_len), *args)
        )
    assert prints[0] == prints[1]


def test_run_results_unchanged_with_inert_plan_installed():
    """An installed plan that never fires must not perturb results —
    the registry is consulted, nothing else changes."""
    a = _engine()
    a.run(4)
    b = _engine()
    with faults.active(FaultPlan("objective.eval", at_call_n=999)):
        b.run(4)
    np.testing.assert_array_equal(_genomes(a), _genomes(b))


# --------------------------------------------------------------- degradation


def _tpu_faked_engine(seed=5, **cfg):
    pga = PGA(seed=seed, config=PGAConfig(use_pallas=True, **cfg))
    pga._pallas_backend_ok = lambda: True  # reach the kernel build on CPU
    pga.create_population(POP, LEN)
    pga.set_objective("onemax")
    return pga


def test_kernel_build_fault_degrades_to_xla_bit_identically(tmp_path):
    from libpga_tpu.utils import telemetry

    ref = _engine()
    ref.run(4)
    path = str(tmp_path / "degraded.jsonl")
    pga = _tpu_faked_engine(
        telemetry=TelemetryConfig(history_gens=0, events_path=path)
    )
    with faults.active(FaultPlan("kernel.build", probability=1.0,
                                 times=None)):
        with pytest.warns(UserWarning, match="degrading this config"):
            pga.run(4)
    np.testing.assert_array_equal(_genomes(pga), _genomes(ref))
    records = telemetry.validate_log(path)
    degraded = [r for r in records if r["event"] == "degraded"]
    assert len(degraded) == 1
    assert "kernel build" in degraded[0]["what"]
    # the degraded config is cached: a second run neither warns nor
    # re-emits (one XLA-path run, no new degradation)
    import warnings as _w

    with faults.active(FaultPlan("kernel.build", probability=1.0,
                                 times=None)):
        with _w.catch_warnings():
            _w.simplefilter("error")
            pga.run(2)
    assert len(
        [r for r in telemetry.validate_log(path) if r["event"] == "degraded"]
    ) == 1


def test_kernel_build_fault_raises_under_raise_policy():
    pga = _tpu_faked_engine(fallback="raise")
    with faults.active(FaultPlan("kernel.build", probability=1.0,
                                 times=None)):
        with pytest.raises(InjectedFault):
            pga.run(2)


def test_fallback_config_validation():
    with pytest.raises(ValueError, match="fallback"):
        PGAConfig(fallback="sideways")


# ---------------------------------------------------------------- supervisor


def test_supervised_plain_run_matches_bare_run():
    bare = _engine()
    bare.run(6)
    sup = _engine()
    report = supervised_run(sup, 6, sleep=lambda s: None)
    assert isinstance(report, SupervisedReport)
    assert report.generations == 6 and report.retries == 0
    np.testing.assert_array_equal(_genomes(bare), _genomes(sup))


def test_supervised_retry_is_bit_identical_and_backoff_grows():
    ref = _engine()
    ref_report = supervised_run(
        ref, 8, checkpoint_every=2, sleep=lambda s: None
    )
    sleeps = []
    pga = _engine()
    with faults.active(
        FaultPlan("objective.eval", at_call_n=2, times=3),
        FaultPlan("objective.eval", at_call_n=3, times=3),
    ):
        report = supervised_run(
            pga, 8, checkpoint_every=2,
            retry=RetryPolicy(max_retries=3, backoff_base_s=0.1,
                              backoff_factor=2.0, jitter=0.5,
                              jitter_seed=0),
            sleep=sleeps.append,
        )
    assert report.retries == 2
    assert len(report.errors) == 2
    np.testing.assert_array_equal(_genomes(ref), _genomes(pga))
    assert report.best_score == ref_report.best_score
    # exponential growth under bounded jitter: attempt k sleeps in
    # [base*2^(k-1)*(1-jitter), base*2^(k-1)]
    assert 0.05 <= sleeps[0] <= 0.1
    assert 0.1 <= sleeps[1] <= 0.2
    # deterministic jitter: same policy seed → same sleeps
    sleeps2 = []
    pga2 = _engine()
    with faults.active(
        FaultPlan("objective.eval", at_call_n=2, times=3),
        FaultPlan("objective.eval", at_call_n=3, times=3),
    ):
        supervised_run(
            pga2, 8, checkpoint_every=2,
            retry=RetryPolicy(max_retries=3, backoff_base_s=0.1,
                              jitter_seed=0),
            sleep=sleeps2.append,
        )
    assert sleeps == sleeps2


def test_supervised_exhausted_retries_reraise():
    pga = _engine()
    with faults.active(
        FaultPlan("objective.eval", probability=1.0, times=None)
    ):
        with pytest.raises(InjectedFault):
            supervised_run(
                pga, 4, retry=RetryPolicy(max_retries=2),
                sleep=lambda s: None,
            )


def test_supervised_nan_storm_rolls_back_and_deterministic_nan_raises():
    ref = _engine()
    supervised_run(ref, 6, checkpoint_every=2, sleep=lambda s: None)
    pga = _engine()
    with faults.active(FaultPlan("objective.eval", kind="nan", at_call_n=2)):
        report = supervised_run(
            pga, 6, checkpoint_every=2, retry=RetryPolicy(max_retries=2),
            sleep=lambda s: None,
        )
    assert report.retries == 1
    assert any("NaNStorm" in e for e in report.errors)
    np.testing.assert_array_equal(_genomes(ref), _genomes(pga))
    # a DETERMINISTIC NaN source exhausts retries and raises NaNStorm
    # instead of silently burning budget on a poisoned population
    pga2 = _engine()
    with faults.active(
        FaultPlan("objective.eval", kind="nan", probability=1.0, times=None)
    ):
        with pytest.raises(NaNStorm):
            supervised_run(
                pga2, 4, retry=RetryPolicy(max_retries=1),
                sleep=lambda s: None,
            )


def test_supervised_auto_checkpoint_cadence_and_meta(tmp_path):
    path = str(tmp_path / "auto.npz")
    pga = _engine()
    report = supervised_run(
        pga, 9, checkpoint_path=path, checkpoint_every=3,
        sleep=lambda s: None,
    )
    # 3 cadence saves + the final save
    assert report.checkpoints == 4
    assert os.path.exists(path)
    meta = read_meta(path)
    assert meta["generations"] == 9 and meta["n"] == 9


def test_supervised_death_and_resume_bit_identical(tmp_path):
    ref = _engine()
    ref_report = supervised_run(
        ref, 8, checkpoint_path=str(tmp_path / "ref.npz"),
        checkpoint_every=2, sleep=lambda s: None,
    )
    path = str(tmp_path / "died.npz")
    dying = _engine()
    with faults.active(FaultPlan("objective.eval", at_call_n=3)):
        with pytest.raises(InjectedFault):
            supervised_run(
                dying, 8, checkpoint_path=path, checkpoint_every=2,
                retry=RetryPolicy(max_retries=0), sleep=lambda s: None,
            )
    assert read_meta(path)["generations"] == 4  # two chunks survived
    # fresh process: seed is irrelevant, state comes from the checkpoint
    resumed = PGA(seed=424242, config=PGAConfig(use_pallas=False))
    resumed.set_objective("onemax")
    report = supervised_run(
        resumed, 8, checkpoint_path=path, checkpoint_every=2, resume=True,
        sleep=lambda s: None,
    )
    assert report.restored and report.generations == 8
    np.testing.assert_array_equal(_genomes(ref), _genomes(resumed))
    assert report.best_score == ref_report.best_score


def test_supervised_stop_hook_drains_at_chunk_boundary(tmp_path):
    """ISSUE 8: the ``stop`` callback ends the run at a chunk boundary
    with the checkpoint + sidecar durable, and a later ``resume=True``
    finishes bit-identical to an uninterrupted same-cadence run — the
    fleet worker's SIGTERM-drain contract."""
    ref = _engine()
    ref_report = supervised_run(
        ref, 8, checkpoint_path=str(tmp_path / "ref.npz"),
        checkpoint_every=2, sleep=lambda s: None,
    )
    path = str(tmp_path / "stopped.npz")
    draining = _engine()
    stop_calls = []

    def stop():  # drain lands during the second chunk
        stop_calls.append(1)
        return len(stop_calls) >= 2

    report = supervised_run(
        draining, 8, checkpoint_path=path, checkpoint_every=2,
        stop=stop, sleep=lambda s: None,
    )
    assert report.stopped and not report.target_reached
    assert report.generations == 4  # stopped after the second chunk
    meta = read_meta(path)
    assert meta["generations"] == 4
    assert meta["ckpt_sig"] is not None  # resume-consistency signature
    resumed = PGA(seed=424242, config=PGAConfig(use_pallas=False))
    resumed.set_objective("onemax")
    report2 = supervised_run(
        resumed, 8, checkpoint_path=path, checkpoint_every=2, resume=True,
        sleep=lambda s: None,
    )
    assert report2.restored and report2.generations == 8
    assert not report2.stopped
    np.testing.assert_array_equal(_genomes(ref), _genomes(resumed))
    assert report2.best_score == ref_report.best_score


def test_supervised_resume_rejects_torn_sidecar_pair(tmp_path):
    """A sidecar whose recorded checkpoint signature does not match the
    checkpoint file (a concurrent writer landed a save mid-resume) is
    re-read instead of trusted blindly; with a persistent mismatch the
    resume proceeds best-effort on the LAST consistent read."""
    import json

    path = str(tmp_path / "pair.npz")
    pga = _engine()
    supervised_run(pga, 4, checkpoint_path=path, checkpoint_every=2,
                   sleep=lambda s: None)
    # Corrupt the signature: pretend the sidecar belongs to a different
    # checkpoint version.
    meta_path = f"{path}.meta.json"
    with open(meta_path) as fh:
        meta = json.load(fh)
    meta["ckpt_sig"] = [0, 0]
    with open(meta_path, "w") as fh:
        json.dump(meta, fh)
    sleeps = []
    resumed = PGA(seed=7, config=PGAConfig(use_pallas=False))
    resumed.set_objective("onemax")
    report = supervised_run(
        resumed, 4, checkpoint_path=path, checkpoint_every=2, resume=True,
        sleep=sleeps.append,
    )
    assert sleeps, "mismatched pair was not re-read"
    assert report.generations == 4  # best-effort completion


def test_supervised_resume_of_completed_run_is_noop(tmp_path):
    path = str(tmp_path / "done.npz")
    pga = _engine()
    supervised_run(pga, 4, checkpoint_path=path, checkpoint_every=2,
                   sleep=lambda s: None)
    before = _genomes(pga)
    again = PGA(seed=1, config=PGAConfig(use_pallas=False))
    again.set_objective("onemax")
    report = supervised_run(
        again, 4, checkpoint_path=path, checkpoint_every=2, resume=True,
        sleep=lambda s: None,
    )
    assert report.generations == 4
    np.testing.assert_array_equal(before, _genomes(again))


def test_supervised_checkpoint_save_fault_retries_chunk(tmp_path):
    ref = _engine()
    supervised_run(
        ref, 6, checkpoint_path=str(tmp_path / "r.npz"),
        checkpoint_every=2, sleep=lambda s: None,
    )
    pga = _engine()
    with faults.active(FaultPlan("checkpoint.save", at_call_n=2)):
        report = supervised_run(
            pga, 6, checkpoint_path=str(tmp_path / "f.npz"),
            checkpoint_every=2, retry=RetryPolicy(max_retries=2),
            sleep=lambda s: None,
        )
    assert report.retries == 1
    np.testing.assert_array_equal(_genomes(ref), _genomes(pga))


def test_supervised_stall_watchdog_aborts():
    # A constant objective can never improve: the stall counter grows
    # every generation and the watchdog must abort instead of burning
    # the remaining budget.
    pga = PGA(
        seed=5,
        config=PGAConfig(
            use_pallas=False, telemetry=TelemetryConfig(history_gens=64)
        ),
    )
    pga.create_population(POP, LEN)
    pga.set_objective(lambda g: jnp.float32(0.0) * jnp.sum(g))
    report = supervised_run(
        pga, 64, checkpoint_every=8, stall_abort_gens=8,
        sleep=lambda s: None,
    )
    assert report.aborted_on_stall
    assert report.generations <= 16  # aborted after the first chunk check


def test_supervised_target_early_stop():
    pga = _engine()
    report = supervised_run(
        pga, 200, target=float(LEN) * 0.6, checkpoint_every=10,
        sleep=lambda s: None,
    )
    assert report.target_reached
    assert report.generations < 200
    assert report.best_score >= LEN * 0.6


def test_supervised_islands():
    ref = PGA(seed=5, config=PGAConfig(use_pallas=False))
    for _ in range(2):
        ref.create_population(POP, LEN)
    ref.set_objective("onemax")
    ref.run_islands(4, 2, 0.1)
    ref.run_islands(4, 2, 0.1)
    pga = PGA(seed=5, config=PGAConfig(use_pallas=False))
    for _ in range(2):
        pga.create_population(POP, LEN)
    pga.set_objective("onemax")
    report = supervised_run(
        pga, 8, islands=(2, 0.1), checkpoint_every=4, sleep=lambda s: None
    )
    assert report.generations == 8
    for i in range(2):
        np.testing.assert_array_equal(
            np.asarray(ref._populations[i].genomes),
            np.asarray(pga._populations[i].genomes),
        )


def test_retry_policy_validation():
    with pytest.raises(ValueError, match="max_retries"):
        RetryPolicy(max_retries=-1)
    with pytest.raises(ValueError, match="jitter"):
        RetryPolicy(jitter=2.0)
    with pytest.raises(ValueError, match="backoff_factor"):
        RetryPolicy(backoff_factor=0.5)


def test_supervised_retry_event_validates(tmp_path):
    from libpga_tpu.utils import telemetry

    path = str(tmp_path / "retry.jsonl")
    pga = _engine(
        tel=TelemetryConfig(history_gens=0, events_path=path)
    )
    with faults.active(FaultPlan("objective.eval", at_call_n=1)):
        supervised_run(
            pga, 4, retry=RetryPolicy(max_retries=1), sleep=lambda s: None
        )
    records = telemetry.validate_log(path)
    retries = [r for r in records if r["event"] == "retry"]
    assert len(retries) == 1
    assert retries[0]["attempt"] == 1 and "error" in retries[0]


# -------------------------------------------------------------- capi bridge


def test_capi_bridge_fault_plan_and_supervised_run(tmp_path):
    from libpga_tpu import capi_bridge as cb

    cb.set_fault_plan(
        '{"seed": 3, "plans": [{"site": "objective.eval", '
        '"at_call_n": 2}]}'
    )
    try:
        assert faults.PLAN is not None
        assert faults.PLAN.seed == 3
        assert faults.PLAN.plans[0].site == "objective.eval"
    finally:
        cb.set_fault_plan("off")
    assert faults.PLAN is None
    with pytest.raises(ValueError):
        cb.set_fault_plan('[{"site": "x", "kind": "bogus", "at_call_n": 1}]')

    h = cb.init(31)
    try:
        cb.create_population(h, POP, LEN, 0)
        cb.set_objective_name(h, "onemax")
        path = str(tmp_path / "cabi.npz")
        gens = cb.supervised_run(h, 6, 2, 1, path, 0)
        assert gens == 6
        assert os.path.exists(path)
        assert read_meta(path)["generations"] == 6
        # resume of the finished run is a no-op returning completion
        h2 = cb.init(99)
        cb.set_objective_name(h2, "onemax")
        assert cb.supervised_run(h2, 6, 2, 1, path, 1) == 6
        cb.deinit(h2)
    finally:
        cb.deinit(h)
