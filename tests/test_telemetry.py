"""In-run telemetry (utils/telemetry): on-device history, spans, events.

The three contracts under test, matching ISSUE 2's acceptance criteria:

- **zero-cost off**: with telemetry disabled the engine's fused run loop
  lowers to the BYTE-IDENTICAL StableHLO of the pre-telemetry code
  (replicated inline here), with no history machinery in it;
- **oracle equivalence**: the per-generation best scores recorded on
  device inside the fused loop match a step-by-step replay — a fresh
  same-seed engine run for exactly ``i`` generations reproduces history
  row ``i-1`` (the fused loop's key chain is length-independent, so the
  trajectories are identical);
- **reachability**: the history is readable from Python
  (``PGA.history``) and through the C-ABI bridge
  (``capi_bridge.set_telemetry``/``get_history``), and the JSONL event
  log validates against the versioned schema.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from libpga_tpu import PGA, PGAConfig, TelemetryConfig
from libpga_tpu.utils import telemetry


def _solver(seed=0, pop=64, length=16, tel=None, **cfg):
    pga = PGA(seed=seed, config=PGAConfig(telemetry=tel, **cfg))
    handle = pga.create_population(pop, length)
    pga.set_objective("onemax")
    return pga, handle


# ------------------------------------------------------------ zero-cost off


def test_disabled_run_loop_lowering_is_unchanged():
    """Telemetry off: the compiled run loop's StableHLO fingerprints
    identically to the pre-telemetry loop (replicated verbatim below —
    ``analysis.fingerprint`` canonicalizes the function-name-derived
    module id, so the replica no longer needs to shadow the engine
    function's name), and contains none of the history machinery;
    enabled differs and does."""
    from libpga_tpu.analysis import canonical_text, fingerprint
    from libpga_tpu.ops.evaluate import evaluate as _evaluate

    pga, h = _solver()
    pop = pga.population(h)
    args = (
        pop.genomes, jax.random.key(0), jnp.int32(3),
        jnp.float32(jnp.inf), pga._mutate_params(),
    )
    compiled = pga._compiled_run(pop.size, pop.genome_len)
    disabled = fingerprint(compiled, *args)

    obj = pga._objective
    breed = pga._breed_fn()

    def run_loop(genomes, key, n, target, mparams):
        del mparams
        scores0 = _evaluate(obj, genomes)

        def cond(carry):
            g, s, k, gen = carry
            return jnp.logical_and(gen < n, jnp.max(s) < target)

        def body(carry):
            g, s, k, gen = carry
            k, sub = jax.random.split(k)
            g2 = breed(g, s, sub)
            s2 = _evaluate(obj, g2)
            return (g2, s2, k, gen + 1)

        init = (genomes, scores0, key, jnp.int32(0))
        g, s, k, gens_done = jax.lax.while_loop(cond, body, init)
        return g, s, gens_done

    reference = fingerprint(run_loop, *args, donate_argnums=(0,))
    assert disabled == reference
    assert "dynamic_update_slice" not in canonical_text(compiled, *args)

    pga2, _ = _solver(tel=TelemetryConfig(history_gens=16))
    enabled_text = canonical_text(
        pga2._compiled_run(pop.size, pop.genome_len), *args
    )
    enabled = fingerprint(pga2._compiled_run(pop.size, pop.genome_len), *args)
    assert enabled != disabled
    assert "dynamic_update_slice" in enabled_text
    assert f"16x{telemetry.NUM_STATS}xf32" in enabled_text  # history carry


def test_disabled_run_returns_no_history():
    pga, h = _solver()
    assert pga.run(3) == 3
    assert pga.history(h) is None


# ------------------------------------------------------- oracle equivalence


def test_history_matches_step_by_step_oracle():
    """History row i must equal what a fresh same-seed engine reports
    after exactly i+1 generations: best via get_best, mean/std via the
    installed scores, diversity via the sampled per-gene variance."""
    N, seed, pop, length = 6, 123, 64, 16
    pga, h = _solver(
        seed=seed, pop=pop, length=length,
        tel=TelemetryConfig(history_gens=32),
    )
    assert pga.run(N) == N
    hist = pga.history(h)
    assert len(hist) == N and not hist.truncated

    for i in range(1, N + 1):
        oracle, oh = _solver(seed=seed, pop=pop, length=length)
        assert oracle.run(i) == i
        _, best = oracle.get_best_with_score(oh)
        scores = np.asarray(oracle.population(oh).scores)
        genomes = np.asarray(
            oracle.population(oh).genomes, dtype=np.float32
        )[: telemetry.DIVERSITY_SAMPLE_ROWS]
        np.testing.assert_allclose(hist.best[i - 1], best, rtol=1e-6)
        np.testing.assert_allclose(hist.mean[i - 1], scores.mean(), rtol=1e-5)
        np.testing.assert_allclose(hist.std[i - 1], scores.std(), rtol=1e-4)
        np.testing.assert_allclose(
            hist.diversity[i - 1], genomes.var(axis=0).mean(), rtol=1e-4
        )


def test_stall_counter_counts_generations_without_improvement():
    """A constant objective never improves after the first generation:
    the stall column must read 1, 2, ..., N."""
    pga, h = _solver(tel=TelemetryConfig(history_gens=16))
    pga.set_objective(lambda g: jnp.sum(g) * 0.0)
    pga.run(5)
    hist = pga.history(h)
    np.testing.assert_array_equal(hist.stall, np.arange(1, 6))
    np.testing.assert_array_equal(hist.best, np.zeros(5))


def test_history_capacity_clamps_to_last_row():
    """Runs longer than the buffer keep the LAST row current and set
    .truncated — never scribbling over earlier rows."""
    pga, h = _solver(seed=5, tel=TelemetryConfig(history_gens=4))
    pga.run(10)
    hist = pga.history(h)
    assert len(hist) == 4 and hist.truncated and hist.generations == 10
    # last row is the generation-10 population (current scores agree)
    scores = np.asarray(pga.population(h).scores)
    np.testing.assert_allclose(hist.best[-1], scores.max(), rtol=1e-6)
    # earlier rows still carry the early trajectory (gen 1..3)
    oracle, oh = _solver(seed=5)
    oracle.run(1)
    np.testing.assert_allclose(
        hist.best[0], oracle.get_best_with_score(oh)[1], rtol=1e-6
    )


def test_target_hit_trims_history_rows():
    pga, h = _solver(tel=TelemetryConfig(history_gens=64))
    pga.evaluate(h)
    # target strictly above the initial best so the loop runs >= 1 gen
    target = pga.get_best_with_score(h)[1] + 0.5
    gens = pga.run(50, target=target)
    hist = pga.history(h)
    assert 1 <= gens <= 50 and len(hist) == gens
    assert hist.best[-1] >= target
    if len(hist) > 1:
        assert (hist.best[:-1] < target).all()


# ----------------------------------------------------------------- islands


def test_islands_history_epoch_granularity():
    pga = PGA(seed=3, config=PGAConfig(
        telemetry=TelemetryConfig(history_gens=16)
    ))
    handles = [pga.create_population(64, 16) for _ in range(4)]
    pga.set_objective("onemax")
    gens = pga.run_islands(7, 2, 0.1)  # 3 epochs of 2 + remainder 1
    assert gens == 7
    hist = pga.history(handles[0])
    assert hist is pga.history(handles[1])  # one shared global history
    assert len(hist) == 7
    assert not np.isnan(hist._rows).any()
    # epoch granularity: rows within one epoch are identical
    np.testing.assert_array_equal(hist.best[0], hist.best[1])
    np.testing.assert_array_equal(hist.best[2], hist.best[3])
    # final row agrees with the installed populations' global best
    best = max(
        float(np.asarray(pga.population(h).scores).max()) for h in handles
    )
    np.testing.assert_allclose(hist.best[-1], best, rtol=1e-6)


def test_islands_history_sharded_matches_local():
    """The sharded runner's collective stats must equal the local
    runner's on the same seed (same trajectory, pmax/pmean-combined
    moments)."""
    from libpga_tpu.utils.compat import shard_map as _shard_map  # noqa: F401
    from jax.sharding import Mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 devices")

    def run(mesh):
        pga = PGA(seed=11, config=PGAConfig(
            telemetry=TelemetryConfig(history_gens=16)
        ))
        for _ in range(4):
            pga.create_population(64, 16)
        pga.set_objective("onemax")
        pga.run_islands(6, 2, 0.1, mesh=mesh)
        return pga.history(pga._handles()[0])

    local = run(None)
    try:
        sharded = run(Mesh(np.array(jax.devices()[:4]), ("islands",)))
    except Exception as e:  # pragma: no cover - backend capability gate
        pytest.skip(f"sharded islands unavailable on this backend: {e}")
    # best is exact (pmax); mean/std combine shard moments in a
    # different accumulation order than the local single reduction —
    # f32-level differences only.
    np.testing.assert_array_equal(local.best, sharded.best)
    np.testing.assert_allclose(local._rows, sharded._rows, rtol=2e-3,
                               atol=1e-4)


# ------------------------------------------------------------- event log


def test_event_log_schema_and_kinds(tmp_path):
    path = str(tmp_path / "events.jsonl")
    pga = PGA(seed=1, config=PGAConfig(
        telemetry=TelemetryConfig(
            history_gens=8, events_path=path, stall_alert_gens=2
        )
    ))
    h = pga.create_population(32, 8)
    pga.create_population(32, 8)
    pga.set_objective(lambda g: jnp.sum(g) * 0.0)  # stalls immediately
    pga.run(5)
    pga.migrate(0.1)
    pga.run_islands(4, 2, 0.1)

    records = telemetry.validate_log(path)  # raises on any schema break
    kinds = [r["event"] for r in records]
    for need in (
        "compile", "run_start", "run_record", "run_end", "stall_alert",
        "migration", "islands_start", "islands_end",
    ):
        assert need in kinds, f"missing event kind {need}: {kinds}"
    run_end = next(r for r in records if r["event"] == "run_end")
    assert run_end["generations"] == 5 and run_end["best"] == 0.0
    alert = next(r for r in records if r["event"] == "stall_alert")
    assert alert["stalled_gens"] >= 2


def test_event_validation_rejects_malformed(tmp_path):
    telemetry.validate_event(
        {"schema": 1, "ts": 0.0, "event": "custom_kind", "x": 1}
    )  # unknown kinds allowed with base keys
    with pytest.raises(ValueError, match="missing required key"):
        telemetry.validate_event({"ts": 0.0, "event": "x"})
    with pytest.raises(ValueError, match="schema"):
        telemetry.validate_event({"schema": 99, "ts": 0.0, "event": "x"})
    with pytest.raises(ValueError, match="missing fields"):
        telemetry.validate_event(
            {"schema": 1, "ts": 0.0, "event": "run_end", "seconds": 1.0}
        )
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"schema": 1, "ts": 0.0, "event": "run_end"}\n')
    with pytest.raises(ValueError, match="bad.jsonl:1"):
        telemetry.validate_log(str(bad))


def test_checkpoint_save_emits_event(tmp_path):
    from libpga_tpu.utils import checkpoint

    path = str(tmp_path / "events.jsonl")
    pga, _ = _solver(tel=TelemetryConfig(history_gens=8, events_path=path))
    pga.run(2)
    checkpoint.save(pga, str(tmp_path / "state.npz"))
    kinds = [r["event"] for r in telemetry.validate_log(path)]
    assert "checkpoint_save" in kinds


# ------------------------------------------------------------ trace spans


def test_trace_smoke_tool(tmp_path):
    """tools/trace_smoke.py end to end: every pga/<stage> span appears
    in a profiler capture (the CI gate, run in-process)."""
    import importlib
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "tools"))
    try:
        trace_smoke = importlib.import_module("trace_smoke")
    finally:
        sys.path.pop(0)
    assert trace_smoke.main(str(tmp_path)) == 0


# ------------------------------------------------------------ C ABI bridge


def test_capi_bridge_history_roundtrip():
    """pga_get_history's bridge surface: set_telemetry + get_history
    return the same rows PGA.history holds, as raw f32 bytes."""
    from libpga_tpu import capi_bridge as cb

    h = cb.init(21)
    try:
        p = cb.create_population(h, 128, 16, 0)
        cb.set_objective_name(h, "onemax")
        assert cb.history_rows(h, p) == 0
        assert cb.get_history(h, p) == b""
        cb.set_telemetry(h, 32)
        assert cb.run(h, 6, 0, 0.0) == 6
        cols = cb.history_cols()
        assert cols == telemetry.NUM_STATS
        rows = cb.history_rows(h, p)
        assert rows == 6
        data = np.frombuffer(cb.get_history(h, p), dtype=np.float32)
        data = data.reshape(rows, cols)
        pga = cb._solver(h)
        from libpga_tpu.engine import PopulationHandle

        hist = pga.history(PopulationHandle(p))
        np.testing.assert_array_equal(data[:, 0], hist.best)
        np.testing.assert_array_equal(
            data[:, 4].astype(np.int32), hist.stall
        )
        # disable: next run records nothing
        cb.set_telemetry(h, 0)
        cb.run(h, 2, 0, 0.0)
        assert cb.history_rows(h, p) == 0
    finally:
        cb.deinit(h)


# ----------------------------------------------- Pallas run-loop variants


def _interpret():
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.force_tpu_interpret_mode()


def test_multigen_run_loop_history_launch_granularity():
    """The multi-generation Pallas run loop's telemetry variant
    (interpret mode): rows land at launch granularity — every row of a
    launch holds the launch-end stats, the final row agrees with the
    returned scores, and the generation count is exact."""
    from libpga_tpu.objectives import get as get_obj
    from libpga_tpu.ops.pallas_step import (
        _multigen_run_loop, make_pallas_multigen,
    )

    P, L, T, N = 512, 20, 3, 7
    obj = get_obj("onemax")
    with _interpret():
        bm = make_pallas_multigen(
            P, L, deme_size=128, fused_obj=obj.kernel_rowwise,
            fused_consts=tuple(getattr(obj, "kernel_rowwise_consts", ())),
        )
        assert bm is not None
        fn = _multigen_run_loop(obj, bm, P, L, T, donate=False,
                                history_gens=16)
        g = jax.random.uniform(jax.random.key(1), (P, L), dtype=jnp.float32)
        g2, s2, gens, buf = fn(
            g, jax.random.key(0), jnp.int32(N), jnp.float32(jnp.inf),
            jnp.asarray([[0.01, 0.0]], dtype=jnp.float32),
        )
    assert int(gens) == N
    hist = telemetry.History(buf, int(gens))
    assert len(hist) == N and not np.isnan(hist._rows).any()
    # launch granularity: rows within one T-chunk are identical
    np.testing.assert_array_equal(hist.best[0], hist.best[T - 1])
    # final row describes the returned population
    np.testing.assert_allclose(
        hist.best[-1], np.asarray(s2).max(), rtol=1e-5
    )
    # stall advances by whole launches when frozen (cheap sanity: the
    # column is non-negative and bounded by the generation count)
    assert (hist.stall >= 0).all() and (hist.stall <= N).all()


def test_islands_history_with_fused_pallas_breed():
    """run_islands_stacked's history threading over a FUSED Pallas
    island breed (interpret mode) — the kernel path records the same
    epoch-granularity global stats as the XLA path."""
    from libpga_tpu.objectives import get as get_obj
    from libpga_tpu.ops.pallas_step import make_pallas_breed
    from libpga_tpu.parallel.islands import run_islands_stacked

    I, S, L = 2, 512, 20
    obj = get_obj("onemax")
    with _interpret():
        breed = make_pallas_breed(
            S, L, deme_size=128, mutation_rate=0.0,
            fused_obj=obj.kernel_rowwise,
        )
        assert breed.fused
        stacked = jax.random.uniform(jax.random.key(0), (I, S, L))
        genomes, scores, gens, buf = run_islands_stacked(
            breed, obj, stacked, jax.random.key(1), n=4, m=2, pct=0.05,
            history_gens=8,
        )
    assert gens == 4
    hist = telemetry.History(buf, gens)
    assert len(hist) == 4 and not np.isnan(hist._rows).any()
    np.testing.assert_array_equal(hist.best[0], hist.best[1])  # epoch rows
    np.testing.assert_allclose(
        hist.best[-1], np.asarray(scores).max(), rtol=1e-5
    )


# ------------------------------------------------------------- unit pieces


def test_device_helpers_write_and_fill():
    """write_row / fill_rows clamp semantics (shared by the Pallas run
    loops, which only build on a real TPU — this covers the helpers the
    kernel-side paths reuse verbatim)."""
    buf = telemetry.history_init(4)
    row = jnp.arange(telemetry.NUM_STATS, dtype=jnp.float32)

    out = np.asarray(jax.jit(telemetry.write_row)(buf, jnp.int32(2), row))
    assert not np.isnan(out[2]).any() and np.isnan(out[[0, 1, 3]]).all()
    # past-capacity write clamps to the last row
    out = np.asarray(jax.jit(telemetry.write_row)(buf, jnp.int32(9), row))
    assert not np.isnan(out[3]).any() and np.isnan(out[:3]).all()

    fill = jax.jit(telemetry.fill_rows)
    out = np.asarray(fill(buf, jnp.int32(1), jnp.int32(3), row))
    assert not np.isnan(out[1:3]).any() and np.isnan(out[[0, 3]]).all()
    # past-capacity chunk clamps to the last row too
    out = np.asarray(fill(buf, jnp.int32(7), jnp.int32(9), row))
    assert not np.isnan(out[3]).any() and np.isnan(out[:3]).all()


def test_telemetry_config_validation():
    with pytest.raises(ValueError, match="history_gens"):
        TelemetryConfig(history_gens=-1)
    with pytest.raises(ValueError, match="stall_alert_gens"):
        TelemetryConfig(stall_alert_gens=-1)
    # history_gens=0 = events-only mode: no history carry
    pga, h = _solver(tel=TelemetryConfig(history_gens=0))
    pga.run(2)
    assert pga.history(h) is None
